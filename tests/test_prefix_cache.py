"""Prefix caching: chunk prefill oracle, block index, and engine reuse.

The contract: a request admitted with cached history must produce EXACTLY
the tokens it would have produced from a cold full prefill — prefix caching
is a pure latency optimization (engine/prefix_cache.py)."""

import asyncio

import jax
import jax.numpy as jnp
import numpy as np

from p2p_llm_tunnel_tpu.engine.engine import EngineConfig, InferenceEngine
from p2p_llm_tunnel_tpu.engine.prefix_cache import (
    PrefixIndex,
    init_pool,
    make_copy_ops,
    pad_ids,
)
from p2p_llm_tunnel_tpu.models.config import get_config
from p2p_llm_tunnel_tpu.models.transformer import (
    chunk_prefill_into_cache,
    init_kv_cache,
    init_params,
    prefill_into_cache,
)
from p2p_llm_tunnel_tpu.ops.attention import causal_attention, history_attention
from p2p_llm_tunnel_tpu.utils.metrics import global_metrics

import pytest

# Compile-heavy (JAX jit of engine/model programs): excluded from
# `make test-fast` (VERDICT r4 item 8).
pytestmark = pytest.mark.slow


# ---------------------------------------------------------------------------
# history_attention
# ---------------------------------------------------------------------------

def test_history_attention_zero_start_equals_causal():
    key = jax.random.PRNGKey(0)
    b, t, h, kh, d = 2, 8, 4, 2, 16
    q = jax.random.normal(key, (b, t, h, d), jnp.float32)
    k = jax.random.normal(jax.random.fold_in(key, 1), (b, t, kh, d))
    v = jax.random.normal(jax.random.fold_in(key, 2), (b, t, kh, d))
    valid = jnp.ones((b, t), bool)
    ref = causal_attention(q, k, v, valid)
    # Cache = exactly the chunk's own KV, starts = 0.
    out = history_attention(q, k, v, jnp.zeros((b,), jnp.int32))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_history_attention_matches_full_causal_with_split():
    """Attending (history + tail) must equal full causal attention over the
    concatenated sequence, restricted to the tail's rows."""
    key = jax.random.PRNGKey(3)
    b, hist, tail, h, kh, d = 2, 8, 4, 4, 2, 16
    t = hist + tail
    q = jax.random.normal(key, (b, t, h, d), jnp.float32)
    k = jax.random.normal(jax.random.fold_in(key, 1), (b, t, kh, d))
    v = jax.random.normal(jax.random.fold_in(key, 2), (b, t, kh, d))
    valid = jnp.ones((b, t), bool)
    ref = causal_attention(q, k, v, valid)[:, hist:]
    out = history_attention(
        q[:, hist:], k, v, jnp.full((b,), hist, jnp.int32)
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


# ---------------------------------------------------------------------------
# chunk prefill vs full prefill (the oracle)
# ---------------------------------------------------------------------------

def _oracle_setup(kv_quant=False):
    cfg = get_config("tiny")
    params = init_params(cfg, jax.random.PRNGKey(7), jnp.float32)
    prompt = list(np.random.RandomState(0).randint(1, 200, size=40))
    return cfg, params, prompt


def test_chunk_prefill_matches_full_prefill():
    cfg, params, prompt = _oracle_setup()
    n, hist = len(prompt), 16
    slots = jnp.array([0])

    cache_a = init_kv_cache(cfg, 2, 64, jnp.float32)
    tok_a = jnp.zeros((1, 64), jnp.int32).at[0, :n].set(jnp.array(prompt))
    last_a, cache_a = prefill_into_cache(
        cfg, params, tok_a, jnp.array([n]), cache_a, slots
    )

    # B: prefill only the prefix, then chunk-prefill the tail with history.
    cache_b = init_kv_cache(cfg, 2, 64, jnp.float32)
    tok_p = jnp.zeros((1, 16), jnp.int32).at[0, :hist].set(
        jnp.array(prompt[:hist])
    )
    _, cache_b = prefill_into_cache(
        cfg, params, tok_p, jnp.array([hist]), cache_b, slots
    )
    tail = prompt[hist:]
    tok_t = jnp.zeros((1, 32), jnp.int32).at[0, : len(tail)].set(
        jnp.array(tail)
    )
    last_b, cache_b = chunk_prefill_into_cache(
        cfg, params, tok_t, jnp.array([len(tail)]),
        jnp.array([hist], jnp.int32), cache_b, slots,
    )

    np.testing.assert_allclose(
        np.asarray(last_b), np.asarray(last_a), atol=2e-4, rtol=2e-4
    )
    # Cache contents agree everywhere a real token was written.
    for key in ("k", "v"):
        np.testing.assert_allclose(
            np.asarray(cache_b[key][:, 0, :n]),
            np.asarray(cache_a[key][:, 0, :n]),
            atol=2e-4, rtol=2e-4,
        )


def test_chunk_prefill_kv_view_equals_full_view():
    """A kv_view bucket covering the live context must be EXACTLY the
    full-view computation (VERDICT r4 #7: admission cost may track the
    view, never the answer)."""
    cfg, params, prompt = _oracle_setup()
    n, hist = len(prompt), 16
    slots = jnp.array([0])
    max_seq = 256  # cache much larger than the live context

    def run(view):
        cache = init_kv_cache(cfg, 2, max_seq, jnp.float32)
        tok_p = jnp.zeros((1, 16), jnp.int32).at[0, :hist].set(
            jnp.array(prompt[:hist])
        )
        _, cache = prefill_into_cache(
            cfg, params, tok_p, jnp.array([hist]), cache, slots
        )
        tail = prompt[hist:]
        tok_t = jnp.zeros((1, 32), jnp.int32).at[0, : len(tail)].set(
            jnp.array(tail)
        )
        return chunk_prefill_into_cache(
            cfg, params, tok_t, jnp.array([len(tail)]),
            jnp.array([hist], jnp.int32), cache, slots, kv_view=view,
        )

    last_small, cache_small = run(64)  # covers hist+tail=~48
    last_full, cache_full = run(None)
    np.testing.assert_allclose(
        np.asarray(last_small), np.asarray(last_full), atol=1e-5, rtol=1e-5
    )
    for key in ("k", "v"):
        np.testing.assert_allclose(
            np.asarray(cache_small[key][:, 0, :n]),
            np.asarray(cache_full[key][:, 0, :n]),
            atol=1e-5, rtol=1e-5,
        )


def test_chunk_prefill_multirow_mixed_histories():
    """Rows with different history lengths (including 0) in ONE call."""
    cfg, params, _ = _oracle_setup()
    rs = np.random.RandomState(1)
    prompts = [list(rs.randint(1, 200, size=m)) for m in (20, 28, 9)]
    hists = [16, 8, 0]

    lasts_ref = []
    cache_a = init_kv_cache(cfg, 4, 64, jnp.float32)
    for i, p in enumerate(prompts):
        tok = jnp.zeros((1, 32), jnp.int32).at[0, : len(p)].set(jnp.array(p))
        last, cache_a = prefill_into_cache(
            cfg, params, tok, jnp.array([len(p)]), cache_a, jnp.array([i])
        )
        lasts_ref.append(np.asarray(last[0]))

    cache_b = init_kv_cache(cfg, 4, 64, jnp.float32)
    for i, (p, h) in enumerate(zip(prompts, hists)):
        if h:
            tok = jnp.zeros((1, 16), jnp.int32).at[0, :h].set(
                jnp.array(p[:h])
            )
            _, cache_b = prefill_into_cache(
                cfg, params, tok, jnp.array([h]), cache_b, jnp.array([i])
            )
    t = 32
    tokens = np.zeros((3, t), np.int32)
    lengths = np.zeros((3,), np.int32)
    for i, (p, h) in enumerate(zip(prompts, hists)):
        tail = p[h:]
        tokens[i, : len(tail)] = tail
        lengths[i] = len(tail)
    lasts, cache_b = chunk_prefill_into_cache(
        cfg, params, jnp.asarray(tokens), jnp.asarray(lengths),
        jnp.asarray(hists, jnp.int32), cache_b, jnp.arange(3),
    )
    for i, (p, ref) in enumerate(zip(prompts, lasts_ref)):
        np.testing.assert_allclose(
            np.asarray(lasts[i]), ref, atol=2e-4, rtol=2e-4
        )


def test_chunk_prefill_int8_kv_cache():
    """Composes with the quantized KV cache (pool + cache share dtypes)."""
    cfg, params, prompt = _oracle_setup()
    n, hist = len(prompt), 16
    slots = jnp.array([0])
    cache_a = init_kv_cache(cfg, 2, 64, jnp.float32, quant=True)
    tok_a = jnp.zeros((1, 64), jnp.int32).at[0, :n].set(jnp.array(prompt))
    last_a, _ = prefill_into_cache(
        cfg, params, tok_a, jnp.array([n]), cache_a, slots
    )
    cache_b = init_kv_cache(cfg, 2, 64, jnp.float32, quant=True)
    tok_p = jnp.zeros((1, 16), jnp.int32).at[0, :hist].set(
        jnp.array(prompt[:hist])
    )
    _, cache_b = prefill_into_cache(
        cfg, params, tok_p, jnp.array([hist]), cache_b, slots
    )
    tail = prompt[hist:]
    tok_t = jnp.zeros((1, 32), jnp.int32).at[0, : len(tail)].set(
        jnp.array(tail)
    )
    last_b, cache_b = chunk_prefill_into_cache(
        cfg, params, tok_t, jnp.array([len(tail)]),
        jnp.array([hist], jnp.int32), cache_b, slots,
    )
    # int8 KV quantization noise: compare coarsely but meaningfully.
    np.testing.assert_allclose(
        np.asarray(last_b), np.asarray(last_a), atol=0.15, rtol=0.1
    )


# ---------------------------------------------------------------------------
# PrefixIndex
# ---------------------------------------------------------------------------

def test_index_match_missing_allocate():
    idx = PrefixIndex(block=4, capacity=8)
    prompt = list(range(1, 14))  # 13 tokens = 3 full blocks + 1
    n, ids = idx.match(prompt)
    assert (n, ids) == (0, [])
    missing = idx.missing(prompt)
    assert [b for b, _ in missing] == [0, 1, 2]
    pool_ids = idx.allocate([k for _, k in missing])
    assert len(set(pool_ids)) == 3 and 0 not in pool_ids  # scratch reserved
    n, ids = idx.match(prompt)
    assert n == 12 and ids == pool_ids
    assert idx.missing(prompt) == []


def test_index_never_matches_whole_prompt():
    """At least one tail token must remain to produce the first logits."""
    idx = PrefixIndex(block=4, capacity=8)
    prompt = list(range(1, 9))  # exactly 2 blocks
    idx.allocate([k for _, k in idx.missing(prompt)])
    n, ids = idx.match(prompt)
    assert n == 4 and len(ids) == 1  # capped at (8-1)//4 = 1 block


def test_index_chain_hash_rejects_same_window_different_prefix():
    idx = PrefixIndex(block=4, capacity=8)
    a = [1, 2, 3, 4, 9, 9, 9, 9, 5]
    b = [7, 7, 7, 7, 9, 9, 9, 9, 5]  # same 2nd block content, different 1st
    idx.allocate([k for _, k in idx.missing(a)])
    n, _ = idx.match(b)
    assert n == 0  # b's first block differs -> chain breaks immediately


def test_index_lru_eviction():
    idx = PrefixIndex(block=2, capacity=3)  # scratch + 2 real blocks
    p1, p2, p3 = [1, 2, 9], [3, 4, 9], [5, 6, 9]
    idx.allocate([k for _, k in idx.missing(p1)])
    idx.allocate([k for _, k in idx.missing(p2)])
    idx.match(p1)  # touch p1 -> p2 becomes LRU
    idx.allocate([k for _, k in idx.missing(p3)])  # evicts p2's block
    assert idx.match(p1)[0] == 2
    assert idx.match(p2)[0] == 0
    assert idx.match(p3)[0] == 2


def test_allocate_never_self_evicts():
    """A prompt with more blocks than the pool must get a PREFIX of pool
    ids (no duplicates, no evicting this call's own keys)."""
    idx = PrefixIndex(block=2, capacity=6)  # scratch + 5 real blocks
    prompt = list(range(1, 18))  # 8 full blocks > capacity
    keys = [k for _, k in idx.missing(prompt)]
    ids = idx.allocate(keys)
    assert len(ids) == 5 and len(set(ids)) == 5
    # The allocated prefix is matchable as a chain prefix.
    n, got = idx.match(prompt)
    assert n == 10 and got == ids


def test_pad_ids_shapes_and_padding():
    pids, bnos = pad_ids([5, 6], [0, 1], 4, scratch=None)
    assert list(pids) == [5, 6, 6, 6] and list(bnos) == [0, 1, 1, 1]
    pids, bnos = pad_ids([5], [2], 3, scratch=0)
    assert list(pids) == [5, 0, 0] and list(bnos) == [2, 2, 2]


# ---------------------------------------------------------------------------
# copy ops
# ---------------------------------------------------------------------------

def test_copy_ops_roundtrip():
    cfg = get_config("tiny")
    block, cap = 4, 6
    cache = init_kv_cache(cfg, 3, 16, jnp.float32)
    key = jax.random.PRNGKey(11)
    cache = {
        k: jax.random.normal(jax.random.fold_in(key, i), v.shape, v.dtype)
        for i, (k, v) in enumerate(cache.items())
    }
    pool = init_pool(cache, block, cap)
    copy_in, copy_out = make_copy_ops(block, 16 // block)

    # Save slot 1's blocks 0..2 into pool ids 3,4,5; then restore into
    # slot 2 and compare against slot 1's original content.
    orig = {k: np.asarray(v) for k, v in cache.items()}
    pids, bnos = pad_ids([3, 4, 5], [0, 1, 2], 4, scratch=0)
    pool = copy_out(pool, cache, 1, pids, bnos)
    pids, bnos = pad_ids([3, 4, 5], [0, 1, 2], 4, scratch=None)
    cache = copy_in(cache, pool, 2, pids, bnos)
    for k in orig:
        np.testing.assert_array_equal(
            np.asarray(cache[k][:, 2, :12]), orig[k][:, 1, :12]
        )
        # Untouched region of slot 2 stays intact.
        np.testing.assert_array_equal(
            np.asarray(cache[k][:, 2, 12:]), orig[k][:, 2, 12:]
        )
        # Other slots untouched.
        np.testing.assert_array_equal(np.asarray(cache[k][:, 0]), orig[k][:, 0])


def test_plan_inserts_same_wave_eviction_keeps_ids_distinct():
    """With a pool smaller than one admission wave, a later run's
    allocation evicts an earlier run's fresh keys.  plan_inserts must drop
    the evicted pairs so the batched scatter never writes two different
    runs' KV into one live pool block, and every surviving (key -> id)
    mapping must match what the index will serve on later hits."""
    from p2p_llm_tunnel_tpu.engine.prefix_cache import plan_inserts

    block = 4
    idx = PrefixIndex(block, capacity=4)  # scratch + 3 real blocks
    # Three runs x 2 blocks = 6 blocks wanted, 3 available: run C's
    # allocation evicts run A's keys (LRU order = insertion order here).
    wave = [
        (0, list(range(100, 100 + 2 * block))),
        (1, list(range(200, 200 + 2 * block))),
        (2, list(range(300, 300 + 2 * block))),
    ]
    entries = plan_inserts(idx, wave)
    # Surviving pool ids are distinct across the whole wave — the batched
    # scatter invariant.
    flat = [i for _, ids, _ in entries for i in ids]
    assert len(flat) == len(set(flat)) and flat
    assert all(i != 0 for i in flat)  # scratch is never a real target
    # Every surviving id is exactly what the index maps that slot's block
    # to now — i.e. later matches will read the content this wave wrote.
    for slot, ids, blks in entries:
        prompt = dict(wave)[slot]
        keys = idx._keys_of(prompt)
        for i, b in zip(ids, blks):
            assert idx.id_of(keys[b]) == i
    # Duplicate prompts across a wave dedupe: the second run has nothing
    # missing once the first allocated, whatever survived eviction.
    idx2 = PrefixIndex(block, capacity=6)
    dup = [(0, list(range(50, 50 + 2 * block))),
           (1, list(range(50, 50 + 2 * block)))]
    entries2 = plan_inserts(idx2, dup)
    assert len(entries2) == 1 and entries2[0][0] == 0
    # Eviction ping-pong: A allocates k->1, C evicts k reusing id 1, D
    # (same prompt as A) re-allocates k back onto id 1.  A's and D's pairs
    # both pass the id_of filter; exactly ONE may reach the scatter.
    idx3 = PrefixIndex(block, capacity=2)  # scratch + one real block
    pp = [(0, list(range(100, 100 + block + 1))),
          (1, list(range(200, 200 + block + 1))),
          (2, list(range(100, 100 + block + 1)))]
    entries3 = plan_inserts(idx3, pp)
    flat3 = [i for _, ids, _ in entries3 for i in ids]
    assert flat3 == [1]  # one surviving write for pool block 1, not two


def test_batch_copy_ops_match_sequential_single_ops():
    """The row-batched programs (one dispatch per admission wave) must be
    bit-identical to running the single-slot ops sequentially, including
    within-row padding and repeated/scratch padding rows."""
    from p2p_llm_tunnel_tpu.engine.prefix_cache import (
        make_batch_copy_ops,
        pad_rows,
    )

    cfg = get_config("tiny")
    block, cap, rows = 4, 8, 3
    max_blocks = 16 // block
    cache = init_kv_cache(cfg, 4, 16, jnp.float32)
    key = jax.random.PRNGKey(23)
    cache = {
        k: jax.random.normal(jax.random.fold_in(key, i), v.shape, v.dtype)
        for i, (k, v) in enumerate(cache.items())
    }
    pool = init_pool(cache, block, cap)
    copy_in, copy_out = make_copy_ops(block, max_blocks)
    bcopy_in, bcopy_out = make_batch_copy_ops(block, max_blocks, rows)

    # Two slots save different numbers of blocks (within-row padding) and
    # only two real rows (row padding targets scratch).
    entries = [(1, [3, 4, 5], [0, 1, 2]), (2, [6, 7], [0, 1])]
    # Both ops donate their first argument — hand each its own copy.
    seq_pool = jax.tree.map(jnp.copy, pool)
    for slot, ids, blks in entries:
        pids, bnos = pad_ids(ids, blks, max_blocks, scratch=0)
        seq_pool = copy_out(seq_pool, cache, slot, pids, bnos)
    slots, pids, bnos = pad_rows(entries, rows, max_blocks, scratch=0)
    bat_pool = bcopy_out(jax.tree.map(jnp.copy, pool), cache, slots, pids,
                         bnos)
    for k in pool:
        # Scratch block 0 content is undefined (padding target) — compare
        # the real blocks only.
        np.testing.assert_array_equal(
            np.asarray(seq_pool[k][:, 1:]), np.asarray(bat_pool[k][:, 1:])
        )

    # Restore into two other slots; batch (with a duplicated padding row)
    # must equal sequential single-slot restores.
    entries_in = [(0, [3, 4, 5], [0, 1, 2]), (3, [6, 7], [0, 1])]
    seq_cache = jax.tree.map(jnp.copy, cache)
    for slot, ids, blks in entries_in:
        p, b = pad_ids(ids, blks, max_blocks, scratch=None)
        seq_cache = copy_in(seq_cache, bat_pool, slot, p, b)
    slots, pids, bnos = pad_rows(entries_in, rows, max_blocks, scratch=None)
    bat_cache = bcopy_in(jax.tree.map(jnp.copy, cache), bat_pool, slots,
                         pids, bnos)
    for k in cache:
        np.testing.assert_array_equal(
            np.asarray(seq_cache[k]), np.asarray(bat_cache[k])
        )


# ---------------------------------------------------------------------------
# engine end-to-end
# ---------------------------------------------------------------------------

def test_engine_prefix_reuse_exact_tokens():
    """Same greedy output with and without the prefix cache, and the cache
    actually hits on repeats."""
    prompt = list(b"You are a helpful assistant. Please answer: what?")

    async def run(prefix_cache):
        eng = InferenceEngine(engine_cfg=EngineConfig(
            model="tiny", num_slots=4, max_seq=128, dtype="float32",
            min_prefill_bucket=16, prefix_cache=prefix_cache,
            prefix_pool_blocks=16,
        ))
        await eng.start()
        outs = []
        for _ in range(3):
            out = []
            async for ev in eng.generate(prompt, max_new_tokens=8,
                                         stop_ids=()):
                out.append(ev.token_id)
            outs.append(out)
        await eng.stop()
        hits = eng._prefix.hits if eng._prefix else 0
        return outs, hits

    global_metrics.reset()
    outs_off, hits_off = asyncio.run(run(False))
    outs_on, hits_on = asyncio.run(run(True))
    assert outs_off[0] == outs_off[1] == outs_off[2]
    assert outs_on == outs_off  # caching must not change tokens
    assert hits_off == 0 and hits_on >= 2  # repeats 2 and 3 hit
    assert global_metrics.counter("engine_prefix_hit_tokens_total") > 0


def test_prefix_pool_survives_engine_restart(tmp_path):
    """VERDICT r4 item 10 / SURVEY §5's optional checkpoint clause: with a
    prefix_cache_dir, a FRESH engine process hits KV cached by a previous
    one — same tokens, nonzero hits on its very first request."""
    prompt = list(b"You are a helpful assistant. Please answer: what?")
    snap = str(tmp_path / "pfx")

    def cfg():
        return EngineConfig(
            model="tiny", num_slots=4, max_seq=128, dtype="float32",
            min_prefill_bucket=16, prefix_cache=True,
            prefix_pool_blocks=16, prefix_cache_dir=snap,
        )

    async def serve_once():
        eng = InferenceEngine(engine_cfg=cfg())
        await eng.start()
        out = []
        async for ev in eng.generate(prompt, max_new_tokens=8, stop_ids=()):
            out.append(ev.token_id)
        hits = eng._prefix.hits
        await eng.stop()  # saves the snapshot
        return out, hits

    out_a, hits_a = asyncio.run(serve_once())
    assert hits_a == 0  # cold pool
    out_b, hits_b = asyncio.run(serve_once())
    assert hits_b >= 1  # warm from the snapshot, first request
    assert out_b == out_a  # reused KV must not change tokens

    # An incompatible engine (different seed => different weights) must
    # refuse the snapshot instead of serving another model's KV.
    from dataclasses import replace as dc_replace

    async def other_seed():
        eng = InferenceEngine(engine_cfg=dc_replace(cfg(), seed=1))
        await eng.start()
        out = []
        async for ev in eng.generate(prompt, max_new_tokens=8, stop_ids=()):
            out.append(ev.token_id)
        hits = eng._prefix.hits
        await eng.stop()
        return out, hits

    _, hits_c = asyncio.run(other_seed())
    assert hits_c == 0


def test_prefix_pool_snapshot_rejects_quant_format_mismatch(tmp_path):
    """ISSUE 2 satellite: a snapshot taken under one weight/KV format or
    int4 group size is REJECTED (not silently reloaded) by an engine
    running another — the cached KV bytes were computed by differently-
    quantized weights, so serving them would be another model's KV."""
    prompt = list(b"You are a helpful assistant. Please answer: what?")
    snap = str(tmp_path / "pfx")

    def cfg(**over):
        base = dict(
            model="tiny", num_slots=4, max_seq=128, dtype="float32",
            min_prefill_bucket=16, prefix_cache=True,
            prefix_pool_blocks=16, prefix_cache_dir=snap,
        )
        base.update(over)
        return EngineConfig(**base)

    async def serve_once(ecfg):
        eng = InferenceEngine(engine_cfg=ecfg)
        meta = eng._prefix_snapshot_meta()
        await eng.start()
        out = []
        async for ev in eng.generate(prompt, max_new_tokens=4, stop_ids=()):
            out.append(ev.token_id)
        hits = eng._prefix.hits
        await eng.stop()  # saves the snapshot under this engine's meta
        return hits, meta

    hits, meta = asyncio.run(serve_once(cfg()))
    assert hits == 0  # cold pool
    # Every quantization pin must be in the manifest.
    for key in ("quant", "kv_quant", "group_size"):
        assert key in meta
    # Same config -> snapshot accepted (the control).
    hits, _ = asyncio.run(serve_once(cfg()))
    assert hits >= 1
    # A different int4 group size alone must reject the snapshot: weights
    # identical here (quant=none), so a hit would prove it reloaded.
    hits, meta2 = asyncio.run(serve_once(cfg(quant_group_size=64)))
    assert meta2["group_size"] == 64
    assert hits == 0
    # A different KV format must reject it too (the bytes aren't even the
    # same dtype); int8-KV engine vs the fp32 snapshot just saved.
    hits, _ = asyncio.run(serve_once(cfg(quant_group_size=64,
                                         kv_quant="int8")))
    assert hits == 0


def test_engine_prefix_shared_prefix_different_tails():
    """Distinct requests sharing a long prefix: every request's output must
    match its own no-cache run."""
    base = list(b"Common system prompt shared by every request here. ")

    async def run(prefix_cache):
        eng = InferenceEngine(engine_cfg=EngineConfig(
            model="tiny", num_slots=4, max_seq=128, dtype="float32",
            min_prefill_bucket=16, prefix_cache=prefix_cache,
            prefix_pool_blocks=16,
        ))
        await eng.start()
        outs = []
        for tail in (b"alpha?", b"beta!", b"gamma."):
            out = []
            async for ev in eng.generate(base + list(tail),
                                         max_new_tokens=6, stop_ids=()):
                out.append(ev.token_id)
            outs.append(out)
        await eng.stop()
        return outs

    assert asyncio.run(run(True)) == asyncio.run(run(False))


def test_engine_prefix_concurrent_batch():
    """Concurrent shared-prefix requests through the slot batch."""
    base = list(b"The quick brown fox jumps over the lazy dog again. ")

    async def run(prefix_cache):
        eng = InferenceEngine(engine_cfg=EngineConfig(
            model="tiny", num_slots=4, max_seq=128, dtype="float32",
            min_prefill_bucket=16, prefix_cache=prefix_cache,
            prefix_pool_blocks=32,
        ))
        await eng.start()
        # Seed the pool, then fan out concurrently.
        first = []
        async for ev in eng.generate(base + list(b"seed"), max_new_tokens=4,
                                     stop_ids=()):
            first.append(ev.token_id)

        async def one(tail):
            out = []
            async for ev in eng.generate(base + list(tail), max_new_tokens=6,
                                         stop_ids=()):
                out.append(ev.token_id)
            return out

        outs = await asyncio.gather(*(one(t) for t in
                                      (b"t1", b"t2", b"t3", b"t4", b"t5")))
        await eng.stop()
        return [first] + list(outs)

    assert asyncio.run(run(True)) == asyncio.run(run(False))


# ---------------------------------------------------------------------------
# ISSUE 18 drive-by: snapshot integrity on the tier-residency import path.
# The PR 16 importer verified the manifest pins and the snap_id pairing but
# spliced the POOL BYTES themselves unverified — a snapshot whose npz was
# damaged (or swapped) after the save splices silently, serving corrupted
# KV.  The fix mirrors the spill tier's page contract: the manifest carries
# a page_checksum over the pool leaves, the loader recomputes it before
# splicing, and the pin loop routes through verify_page_pin — THE
# registered boundary check (TC18/TC20) — instead of an inline comparison.
# ---------------------------------------------------------------------------


def _snapshot_fixture(tmp_path):
    """A synthetic saved snapshot: 2-leaf pool + 1-page index."""
    from p2p_llm_tunnel_tpu.engine.prefix_cache import (
        load_pool_snapshot,
        save_pool_snapshot,
    )

    pool = {
        "k": jnp.arange(64, dtype=jnp.float32).reshape(4, 16),
        "v": jnp.arange(64, 128, dtype=jnp.float32).reshape(4, 16),
    }
    index = PrefixIndex(block=16, capacity=4)
    index.import_state([["clock", 0.0], ["ab" * 16, 1, 2.0, 0, 2.0]])
    meta = {"quant": "none", "kv_quant": "off", "group_size": 128}
    save_pool_snapshot(str(tmp_path), pool, index, meta)
    return pool, meta, load_pool_snapshot, save_pool_snapshot


def test_pool_snapshot_roundtrip_direct(tmp_path):
    """Control: an untouched snapshot restores bytes AND index."""
    pool, meta, load, _save = _snapshot_fixture(tmp_path)
    fresh = PrefixIndex(block=16, capacity=4)
    out = load(str(tmp_path), {k: jnp.zeros_like(v) for k, v in pool.items()},
               fresh, meta)
    assert out is not None
    for key in pool:
        np.testing.assert_array_equal(np.asarray(out[key]),
                                      np.asarray(pool[key]))
    assert len(fresh._lru) == 1


def test_pool_snapshot_rejects_corrupt_pool_bytes(tmp_path):
    """A snapshot whose pool bytes were altered AFTER the save — same
    shapes, same snap_id, a legitimately re-written npz so no zip-level
    error fires — must start cold, not splice damaged KV."""
    import os

    pool, meta, load, _save = _snapshot_fixture(tmp_path)
    npz_path = os.path.join(str(tmp_path), "prefix_pool.npz")
    with np.load(npz_path) as npz:
        arrays = {k: npz[k].copy() for k in npz.files}
    arrays["k"][1, 3] += 1.0  # one flipped element
    with open(npz_path, "wb") as f:
        np.savez(f, **arrays)

    fresh = PrefixIndex(block=16, capacity=4)
    out = load(str(tmp_path), {k: jnp.zeros_like(v) for k, v in pool.items()},
               fresh, meta)
    assert out is None, "corrupt pool bytes must not splice"
    assert len(fresh._lru) == 0, "index must stay untouched on refusal"


def test_pool_snapshot_rejects_pre_checksum_manifest_version(tmp_path):
    """A version-2 (pre-checksum) manifest has no pool_checksum to verify
    — the loader must refuse it rather than trust unverifiable bytes."""
    import json as _json
    import os

    pool, meta, load, _save = _snapshot_fixture(tmp_path)
    man_path = os.path.join(str(tmp_path), "prefix_index.json")
    with open(man_path) as f:
        manifest = _json.load(f)
    assert manifest["version"] == 3
    assert "pool_checksum" in manifest
    manifest["version"] = 2
    del manifest["pool_checksum"]
    with open(man_path, "w") as f:
        _json.dump(manifest, f)

    fresh = PrefixIndex(block=16, capacity=4)
    out = load(str(tmp_path), {k: jnp.zeros_like(v) for k, v in pool.items()},
               fresh, meta)
    assert out is None


def test_pool_snapshot_pin_loop_routes_verify_page_pin(tmp_path, monkeypatch):
    """Runtime agreement with the static rules: the loader's compatibility
    gate IS verify_page_pin (the TC18/TC20 registered check), not an inline
    reimplementation — a monkeypatched always-refuse pin check must force a
    cold start even on a pristine snapshot."""
    from p2p_llm_tunnel_tpu.engine import prefix_cache as pc

    pool, meta, load, _save = _snapshot_fixture(tmp_path)

    def refuse(page, m, want):
        raise pc.PagePinError("refused by test")

    monkeypatch.setattr(pc, "verify_page_pin", refuse)
    fresh = PrefixIndex(block=16, capacity=4)
    out = load(str(tmp_path), {k: jnp.zeros_like(v) for k, v in pool.items()},
               fresh, meta)
    assert out is None
