"""Protocol codec + negotiation tests.

Ports the semantics of the reference's 24-test matrix (tunnel/src/protocol.rs
:265-550): roundtrips for every payload-bearing type, corrupt input, boundary
cases, version negotiation, feature intersection — plus wire-format golden
bytes so byte-compatibility with the reference binary is pinned down.
"""

import json

import pytest

from p2p_llm_tunnel_tpu.protocol import (
    MAX_BODY_CHUNK,
    MAX_FRAME_SIZE,
    PROTOCOL_NAME,
    PROTOCOL_VERSION,
    Agree,
    Hello,
    MessageType,
    NegotiationError,
    ProtocolError,
    RequestHeaders,
    ResponseHeaders,
    TunnelMessage,
)
from p2p_llm_tunnel_tpu.protocol.frames import iter_body_chunks


# --- wire format goldens -------------------------------------------------

def test_wire_layout_golden():
    """Header is [type:u8][stream_id:u32 BE]; payload follows verbatim."""
    msg = TunnelMessage(MessageType.RES_BODY, 0x01020304, b"abc")
    assert msg.encode() == bytes([21, 1, 2, 3, 4]) + b"abc"


def test_wire_layout_req_end():
    assert TunnelMessage.req_end(7).encode() == bytes([12, 0, 0, 0, 7])


def test_constants_match_reference():
    assert PROTOCOL_VERSION == 1
    assert PROTOCOL_NAME == "httptunnel"
    assert MAX_FRAME_SIZE == 65536
    assert MAX_BODY_CHUNK == 65408


# --- roundtrips for every payload-bearing type ---------------------------

def test_hello_roundtrip():
    encoded = TunnelMessage.hello().encode()
    decoded = TunnelMessage.decode(encoded)
    assert decoded.msg_type == MessageType.HELLO
    assert decoded.stream_id == 0
    hello = Hello.from_json(decoded.payload)
    assert hello.proto == PROTOCOL_NAME
    assert hello.min_version == 1
    assert hello.max_version == PROTOCOL_VERSION
    assert hello.features == ["sse", "flow", "kvpages"]


def test_hello_json_keys():
    obj = json.loads(TunnelMessage.hello().payload)
    assert set(obj) == {"proto", "min_version", "max_version", "features"}


def test_agree_roundtrip():
    agree = Agree(version=1, features=["sse"])
    decoded = TunnelMessage.decode(TunnelMessage.agree(agree).encode())
    assert decoded.msg_type == MessageType.AGREE
    parsed = Agree.from_json(decoded.payload)
    assert parsed.version == 1
    assert parsed.features == ["sse"]


def test_req_headers_roundtrip():
    rh = RequestHeaders(
        stream_id=42,
        method="POST",
        path="/v1/chat/completions",
        headers={"content-type": "application/json", "x-custom": "1"},
    )
    decoded = TunnelMessage.decode(TunnelMessage.req_headers(rh).encode())
    assert decoded.msg_type == MessageType.REQ_HEADERS
    assert decoded.stream_id == 42
    parsed = RequestHeaders.from_json(decoded.payload)
    assert parsed == rh


def test_req_headers_json_keys():
    rh = RequestHeaders(stream_id=1, method="GET", path="/x", headers={})
    assert set(json.loads(rh.to_json())) == {"stream_id", "method", "path", "headers"}


def test_res_headers_roundtrip():
    rh = ResponseHeaders(
        stream_id=9, status=200, headers={"content-type": "text/event-stream"}
    )
    decoded = TunnelMessage.decode(TunnelMessage.res_headers(rh).encode())
    assert decoded.msg_type == MessageType.RES_HEADERS
    assert decoded.stream_id == 9
    assert ResponseHeaders.from_json(decoded.payload) == rh


def test_req_body_roundtrip():
    decoded = TunnelMessage.decode(TunnelMessage.req_body(3, b"hello body").encode())
    assert decoded.msg_type == MessageType.REQ_BODY
    assert decoded.stream_id == 3
    assert decoded.payload == b"hello body"


def test_res_body_roundtrip():
    data = bytes(range(256)) * 4
    decoded = TunnelMessage.decode(TunnelMessage.res_body(5, data).encode())
    assert decoded.msg_type == MessageType.RES_BODY
    assert decoded.payload == data


def test_end_frames_roundtrip():
    for ctor, mt in [
        (TunnelMessage.req_end, MessageType.REQ_END),
        (TunnelMessage.res_end, MessageType.RES_END),
    ]:
        decoded = TunnelMessage.decode(ctor(11).encode())
        assert decoded.msg_type == mt
        assert decoded.stream_id == 11
        assert decoded.payload == b""


def test_ping_pong_roundtrip():
    for ctor, mt in [
        (TunnelMessage.ping, MessageType.PING),
        (TunnelMessage.pong, MessageType.PONG),
    ]:
        decoded = TunnelMessage.decode(ctor().encode())
        assert decoded.msg_type == mt
        assert decoded.stream_id == 0
        assert decoded.payload == b""


def test_error_roundtrip_plain_text():
    """ERROR payload is plain UTF-8 text, not JSON (reference protocol.rs:240)."""
    decoded = TunnelMessage.decode(TunnelMessage.error(8, "upstream died").encode())
    assert decoded.msg_type == MessageType.ERROR
    assert decoded.stream_id == 8
    assert decoded.payload == b"upstream died"


# --- corrupt input -------------------------------------------------------

def test_decode_empty():
    with pytest.raises(ProtocolError):
        TunnelMessage.decode(b"")


def test_decode_truncated_header():
    with pytest.raises(ProtocolError):
        TunnelMessage.decode(bytes([1, 0, 0]))


def test_decode_unknown_type():
    with pytest.raises(ProtocolError):
        TunnelMessage.decode(bytes([77, 0, 0, 0, 1]) + b"x")


def test_decode_oversize():
    with pytest.raises(ProtocolError):
        TunnelMessage.decode(bytes([21, 0, 0, 0, 1]) + b"x" * MAX_FRAME_SIZE)


def test_encode_oversize():
    with pytest.raises(ProtocolError):
        TunnelMessage(MessageType.RES_BODY, 1, b"x" * (MAX_FRAME_SIZE - 4)).encode()


# --- boundary cases ------------------------------------------------------

def test_header_only_frame():
    decoded = TunnelMessage.decode(bytes([3, 0, 0, 0, 0]))
    assert decoded.msg_type == MessageType.PING
    assert decoded.payload == b""


def test_stream_id_zero_and_max():
    for sid in (0, 2**32 - 1):
        decoded = TunnelMessage.decode(TunnelMessage.req_body(sid, b"x").encode())
        assert decoded.stream_id == sid


def test_max_size_payload():
    data = b"\xab" * MAX_BODY_CHUNK
    encoded = TunnelMessage.res_body(1, data).encode()
    assert len(encoded) == 5 + MAX_BODY_CHUNK
    assert TunnelMessage.decode(encoded).payload == data


def test_empty_payload_body_frame():
    decoded = TunnelMessage.decode(TunnelMessage.res_body(1, b"").encode())
    assert decoded.payload == b""


def test_iter_body_chunks():
    data = b"z" * (MAX_BODY_CHUNK * 2 + 100)
    chunks = list(iter_body_chunks(data))
    assert [len(c) for c in chunks] == [MAX_BODY_CHUNK, MAX_BODY_CHUNK, 100]
    assert b"".join(chunks) == data
    assert list(iter_body_chunks(b"")) == []


# --- version negotiation -------------------------------------------------

def test_negotiate_exact_match():
    agree = Agree.from_hello(Hello())
    assert agree.version == PROTOCOL_VERSION
    assert agree.features == ["sse", "flow", "kvpages"]


def test_negotiate_overlap_picks_highest():
    # Peer supports 1-3, we support exactly 1 → agree on 1.
    hello = Hello(proto=PROTOCOL_NAME, min_version=1, max_version=3, features=["sse"])
    assert Agree.from_hello(hello).version == 1


def test_negotiate_disjoint_versions():
    hello = Hello(proto=PROTOCOL_NAME, min_version=5, max_version=9, features=[])
    with pytest.raises(NegotiationError):
        Agree.from_hello(hello)


def test_negotiate_wrong_protocol():
    with pytest.raises(NegotiationError):
        Agree.from_hello(Hello(proto="ftp", min_version=1, max_version=1))


def test_negotiate_feature_intersection():
    hello = Hello(features=["sse", "compression", "multiplex-v2"])
    assert Agree.from_hello(hello).features == ["sse"]


def test_negotiate_disjoint_features():
    hello = Hello(features=["compression"])
    assert Agree.from_hello(hello).features == []


def test_hello_defaults():
    hello = Hello()
    assert hello.proto == PROTOCOL_NAME
    assert hello.min_version == 1
    assert hello.max_version == PROTOCOL_VERSION
    assert hello.features == ["sse", "flow", "kvpages"]
