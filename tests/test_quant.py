"""Int8 weight-only quantization: structure, accuracy, engine integration."""

import asyncio

import jax
import jax.numpy as jnp
import numpy as np

from p2p_llm_tunnel_tpu.models.config import get_config
from p2p_llm_tunnel_tpu.models.quant import QTensor, mm, quantize_params
from p2p_llm_tunnel_tpu.models.transformer import init_params, prefill

import pytest

# Compile-heavy (JAX jit of engine/model programs): excluded from
# `make test-fast` (VERDICT r4 item 8).
pytestmark = pytest.mark.slow


def test_qtensor_roundtrip_error_bounded():
    w = jax.random.normal(jax.random.PRNGKey(0), (64, 128), jnp.float32)
    from p2p_llm_tunnel_tpu.models.quant import _quantize

    qt = _quantize(w, axis=0)
    assert qt.q.dtype == jnp.int8
    assert qt.scale.shape == (128,)
    deq = np.asarray(qt.q, np.float32) * np.asarray(qt.scale)[None, :]
    err = np.abs(deq - np.asarray(w)).max()
    # max error per channel is scale/2 = absmax/254
    assert err <= np.abs(np.asarray(w)).max() / 127


def test_mm_matches_dequant():
    w = jax.random.normal(jax.random.PRNGKey(1), (32, 16), jnp.float32)
    x = jax.random.normal(jax.random.PRNGKey(2), (4, 32), jnp.float32)
    from p2p_llm_tunnel_tpu.models.quant import _quantize

    qt = _quantize(w, axis=0)
    got = np.asarray(mm(x, qt))
    deq = np.asarray(qt.q, np.float32) * np.asarray(qt.scale)[None, :]
    want = np.asarray(x) @ deq
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def _logit_agreement(cfg, params, qparams):
    tokens = jnp.arange(24)[None, :] % cfg.vocab_size
    valid = jnp.ones_like(tokens, bool)
    ref, _, _ = jax.jit(lambda p: prefill(cfg, p, tokens, valid))(params)
    got, _, _ = jax.jit(lambda p: prefill(cfg, p, tokens, valid))(qparams)
    return np.asarray(ref), np.asarray(got)


def test_quantized_forward_tracks_fp32_llama(cpu_devices):
    cfg = get_config("tiny")
    params = init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    qparams = quantize_params(params)
    ref, got = _logit_agreement(cfg, params, qparams)
    # int8 weight-only should keep argmax mostly identical on random weights
    agree = (ref.argmax(-1) == got.argmax(-1)).mean()
    assert agree > 0.9, f"argmax agreement too low: {agree}"
    # and logits numerically close in an absolute sense
    assert np.abs(ref - got).mean() < 0.05


def test_quantized_forward_tracks_fp32_gemma(cpu_devices):
    cfg = get_config("tiny-gemma")
    params = init_params(cfg, jax.random.PRNGKey(3), jnp.float32)
    qparams = quantize_params(params)
    ref, got = _logit_agreement(cfg, params, qparams)
    agree = (ref.argmax(-1) == got.argmax(-1)).mean()
    assert agree > 0.9, f"argmax agreement too low: {agree}"


def test_engine_with_int8(cpu_devices):
    from p2p_llm_tunnel_tpu.engine.engine import EngineConfig, InferenceEngine

    eng = InferenceEngine(
        engine_cfg=EngineConfig(model="tiny", num_slots=2, max_seq=64,
                                dtype="float32", decode_steps=2, quant="int8")
    )
    assert isinstance(eng.params["blocks"]["wq"], QTensor)

    async def main():
        await eng.start()
        toks = []
        async for ev in eng.generate(list(b"quantized"), max_new_tokens=6,
                                     stop_ids=()):
            toks.append(ev.token_id)
        await eng.stop()
        return toks

    toks = asyncio.run(asyncio.wait_for(main(), 120))
    assert len(toks) == 6


def test_w8a8_mm_tracks_float():
    """act_quant=True path: dynamic int8 activations × int8 weights."""
    w = jax.random.normal(jax.random.PRNGKey(7), (64, 48), jnp.float32)
    x = jax.random.normal(jax.random.PRNGKey(8), (5, 64), jnp.float32)
    from p2p_llm_tunnel_tpu.models.quant import _quantize

    qt = _quantize(w, axis=0)
    got = np.asarray(mm(x, qt, act_quant=True))
    want = np.asarray(x) @ np.asarray(w)
    # two int8 quantizations compound: compare relative to magnitude
    denom = np.abs(want).mean() + 1e-6
    assert np.abs(got - want).mean() / denom < 0.05


def test_w8a8_head_matmul_tracks_float():
    from p2p_llm_tunnel_tpu.models.quant import _quantize, head_matmul

    embed = jax.random.normal(jax.random.PRNGKey(9), (96, 64), jnp.float32)
    x = jax.random.normal(jax.random.PRNGKey(10), (3, 64), jnp.float32)
    qt = _quantize(embed, axis=1)  # per-vocab-row, as quantize_params does
    got = np.asarray(head_matmul(x, qt, act_quant=True))
    want = np.asarray(x) @ np.asarray(embed).T
    denom = np.abs(want).mean() + 1e-6
    assert np.abs(got - want).mean() / denom < 0.05


def test_w8a8_prefill_tracks_fp32(cpu_devices):
    """Full-model forward with dynamic activation quant stays close enough
    for argmax agreement — the accuracy bar for using it in serving."""
    from dataclasses import replace

    cfg = get_config("tiny")
    params = init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    qparams = quantize_params(params)
    aq_cfg = replace(cfg, act_quant=True)
    tokens = jnp.arange(24)[None, :] % cfg.vocab_size
    valid = jnp.ones_like(tokens, bool)
    ref, _, _ = jax.jit(lambda p: prefill(cfg, p, tokens, valid))(params)
    got, _, _ = jax.jit(lambda p: prefill(aq_cfg, p, tokens, valid))(qparams)
    ref, got = np.asarray(ref), np.asarray(got)
    agree = (ref.argmax(-1) == got.argmax(-1)).mean()
    assert agree > 0.85, f"argmax agreement too low: {agree}"


def test_engine_with_w8a8(cpu_devices):
    from p2p_llm_tunnel_tpu.engine.engine import EngineConfig, InferenceEngine

    eng = InferenceEngine(
        engine_cfg=EngineConfig(model="tiny", num_slots=2, max_seq=64,
                                dtype="float32", decode_steps=2, quant="w8a8")
    )
    assert isinstance(eng.params["blocks"]["wq"], QTensor)
    assert eng.mcfg.act_quant

    async def main():
        await eng.start()
        toks = []
        async for ev in eng.generate(list(b"quantized"), max_new_tokens=6,
                                     stop_ids=()):
            toks.append(ev.token_id)
        await eng.stop()
        return toks

    toks = asyncio.run(asyncio.wait_for(main(), 120))
    assert len(toks) == 6


def test_init_params_quantized_single_jit(cpu_devices):
    """Whole-tree int8 init returns QTensor leaves with the right shapes."""
    from p2p_llm_tunnel_tpu.models.quant import init_params_quantized

    cfg = get_config("tiny")
    params = init_params_quantized(cfg, jax.random.PRNGKey(0))
    wq = params["blocks"]["wq"]
    assert isinstance(wq, QTensor)
    assert wq.q.dtype == jnp.int8
    assert wq.q.shape == (cfg.n_layers, cfg.dim, cfg.n_heads * cfg.head_dim)
    assert params["embed"].q.shape == (cfg.vocab_size, cfg.dim)


def test_engine_prefill_act_quant(cpu_devices):
    """prefill_act_quant: prefill runs W8A8, decode stays weight-only —
    generation must work end to end and the decode config stays unchanged."""
    from p2p_llm_tunnel_tpu.engine.engine import EngineConfig, InferenceEngine

    eng = InferenceEngine(
        engine_cfg=EngineConfig(model="tiny", num_slots=2, max_seq=64,
                                dtype="float32", decode_steps=2, quant="int8",
                                prefill_act_quant=True)
    )
    assert eng._prefill_mcfg.act_quant
    assert not eng.mcfg.act_quant

    async def main():
        await eng.start()
        toks = []
        async for ev in eng.generate(list(b"pf8"), max_new_tokens=5,
                                     stop_ids=()):
            toks.append(ev.token_id)
        await eng.stop()
        return toks

    toks = asyncio.run(asyncio.wait_for(main(), 120))
    assert len(toks) == 5


def test_kv_cache_int8_decode_tracks_fp32(cpu_devices):
    """Int8 KV cache: token-by-token decode must track the fp32-cache path
    closely (per-token-per-head scales bound the error) and agree on
    argmax — the accuracy bar for serving with a quantized cache."""
    from p2p_llm_tunnel_tpu.models.transformer import (
        decode_step, init_kv_cache, init_params, prefill_into_cache,
    )

    cfg = get_config("tiny")
    params = init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    t = 12
    prompt_len = 6
    tokens = jax.random.randint(jax.random.PRNGKey(4), (1, t), 0,
                                cfg.vocab_size)

    def run(quant):
        cache = init_kv_cache(cfg, 2, 32, jnp.float32, quant=quant)
        _, cache = prefill_into_cache(
            cfg, params,
            jnp.pad(tokens[:, :prompt_len], ((0, 0), (0, 2))),
            jnp.array([prompt_len]), cache, jnp.array([1]),
        )
        outs = []
        for pos in range(prompt_len, t):
            step_tokens = jnp.zeros((2,), jnp.int32).at[1].set(tokens[0, pos])
            step_pos = jnp.zeros((2,), jnp.int32).at[1].set(pos)
            logits, cache = decode_step(cfg, params, cache, step_tokens,
                                        step_pos)
            outs.append(np.asarray(logits[1]))
        return np.stack(outs)

    ref = run(False)
    got = run(True)
    agree = (ref.argmax(-1) == got.argmax(-1)).mean()
    assert agree >= 0.95, f"argmax agreement too low: {agree}"
    denom = np.abs(ref).mean() + 1e-6
    assert np.abs(ref - got).mean() / denom < 0.1


def test_kv_cache_int8_respects_kv_view(cpu_devices):
    """View bucketing composes with the quantized cache (scales slice with
    the values)."""
    from p2p_llm_tunnel_tpu.models.transformer import (
        decode_step, init_kv_cache, init_params, prefill_into_cache,
    )

    cfg = get_config("tiny")
    params = init_params(cfg, jax.random.PRNGKey(1), jnp.float32)
    cache = init_kv_cache(cfg, 2, 64, jnp.float32, quant=True)
    _, cache = prefill_into_cache(
        cfg, params, jnp.arange(8)[None, :] % cfg.vocab_size,
        jnp.array([8]), cache, jnp.array([0]),
    )
    cache_b = jax.tree.map(lambda x: x, cache)
    toks = jnp.full((2,), 3, jnp.int32)
    pos = jnp.full((2,), 8, jnp.int32)
    full, _ = decode_step(cfg, params, cache, toks, pos)
    view, _ = decode_step(cfg, params, cache_b, toks, pos, kv_view=16)
    np.testing.assert_allclose(np.asarray(full), np.asarray(view),
                               rtol=1e-5, atol=1e-5)


def test_engine_with_int4(cpu_devices):
    """quant='int4': injected fp32 weights quantize to QTensor4 at startup
    and the engine generates end to end; group_size threads through."""
    from p2p_llm_tunnel_tpu.engine.engine import EngineConfig, InferenceEngine
    from p2p_llm_tunnel_tpu.models.quant import QTensor4

    eng = InferenceEngine(
        engine_cfg=EngineConfig(model="tiny", num_slots=2, max_seq=64,
                                dtype="float32", decode_steps=2,
                                quant="int4", quant_group_size=32,
                                prefix_cache=True, prefix_pool_blocks=8)
    )
    wq = eng.params["blocks"]["wq"]
    assert isinstance(wq, QTensor4)
    assert wq.group_size == 32
    assert eng._prefix_snapshot_meta()["group_size"] == 32

    async def main():
        await eng.start()
        toks = []
        async for ev in eng.generate(list(b"int4"), max_new_tokens=6,
                                     stop_ids=()):
            toks.append(ev.token_id)
        await eng.stop()
        return toks

    toks = asyncio.run(asyncio.wait_for(main(), 120))
    assert len(toks) == 6


def test_engine_int4_tokens_match_dequant_reference(cpu_devices):
    """E2e acceptance (ISSUE 2): an int4 engine's greedy tokens equal a
    quant='none' engine serving the SAME int4 weights explicitly
    dequantized — packing is storage, not math."""
    from p2p_llm_tunnel_tpu.engine.engine import EngineConfig, InferenceEngine
    from p2p_llm_tunnel_tpu.models.quant import QTensor4, _dequant4

    def cfg(quant):
        return EngineConfig(model="tiny", num_slots=2, max_seq=64,
                            dtype="bfloat16", decode_steps=2, quant=quant,
                            quant_group_size=32)

    async def run(engine_cfg, params=None):
        eng = InferenceEngine(engine_cfg=engine_cfg, params=params)
        await eng.start()
        toks = []
        async for ev in eng.generate(list(b"identical?"), max_new_tokens=8,
                                     stop_ids=()):
            toks.append(ev.token_id)
        await eng.stop()
        return eng, toks

    async def main():
        eng_q, toks_q = await run(cfg("int4"))
        # bf16 dequant: the dtype the quantized path actually computes in.
        ref_params = jax.tree.map(
            lambda leaf: _dequant4(leaf, jnp.bfloat16)
            if isinstance(leaf, QTensor4) else leaf,
            eng_q.params,
            is_leaf=lambda leaf: isinstance(leaf, QTensor4),
        )
        _, toks_ref = await run(cfg("none"), params=ref_params)
        return toks_q, toks_ref

    toks_q, toks_ref = asyncio.run(asyncio.wait_for(main(), 240))
    assert toks_q == toks_ref


def test_engine_with_kv_quant(cpu_devices):
    from p2p_llm_tunnel_tpu.engine.engine import EngineConfig, InferenceEngine

    eng = InferenceEngine(
        engine_cfg=EngineConfig(model="tiny", num_slots=2, max_seq=64,
                                dtype="float32", decode_steps=2,
                                quant="int8", kv_quant="int8")
    )
    assert "k_scale" in eng.kv_cache

    async def main():
        await eng.start()
        toks = []
        async for ev in eng.generate(list(b"kv-quantized"), max_new_tokens=6,
                                     stop_ids=()):
            toks.append(ev.token_id)
        await eng.stop()
        return toks

    toks = asyncio.run(asyncio.wait_for(main(), 120))
    assert len(toks) == 6
