"""Int8 weight-only quantization: structure, accuracy, engine integration."""

import asyncio

import jax
import jax.numpy as jnp
import numpy as np

from p2p_llm_tunnel_tpu.models.config import get_config
from p2p_llm_tunnel_tpu.models.quant import QTensor, mm, quantize_params
from p2p_llm_tunnel_tpu.models.transformer import init_params, prefill


def test_qtensor_roundtrip_error_bounded():
    w = jax.random.normal(jax.random.PRNGKey(0), (64, 128), jnp.float32)
    from p2p_llm_tunnel_tpu.models.quant import _quantize

    qt = _quantize(w, axis=0)
    assert qt.q.dtype == jnp.int8
    assert qt.scale.shape == (128,)
    deq = np.asarray(qt.q, np.float32) * np.asarray(qt.scale)[None, :]
    err = np.abs(deq - np.asarray(w)).max()
    # max error per channel is scale/2 = absmax/254
    assert err <= np.abs(np.asarray(w)).max() / 127


def test_mm_matches_dequant():
    w = jax.random.normal(jax.random.PRNGKey(1), (32, 16), jnp.float32)
    x = jax.random.normal(jax.random.PRNGKey(2), (4, 32), jnp.float32)
    from p2p_llm_tunnel_tpu.models.quant import _quantize

    qt = _quantize(w, axis=0)
    got = np.asarray(mm(x, qt))
    deq = np.asarray(qt.q, np.float32) * np.asarray(qt.scale)[None, :]
    want = np.asarray(x) @ deq
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def _logit_agreement(cfg, params, qparams):
    tokens = jnp.arange(24)[None, :] % cfg.vocab_size
    valid = jnp.ones_like(tokens, bool)
    ref, _, _ = jax.jit(lambda p: prefill(cfg, p, tokens, valid))(params)
    got, _, _ = jax.jit(lambda p: prefill(cfg, p, tokens, valid))(qparams)
    return np.asarray(ref), np.asarray(got)


def test_quantized_forward_tracks_fp32_llama(cpu_devices):
    cfg = get_config("tiny")
    params = init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    qparams = quantize_params(params)
    ref, got = _logit_agreement(cfg, params, qparams)
    # int8 weight-only should keep argmax mostly identical on random weights
    agree = (ref.argmax(-1) == got.argmax(-1)).mean()
    assert agree > 0.9, f"argmax agreement too low: {agree}"
    # and logits numerically close in an absolute sense
    assert np.abs(ref - got).mean() < 0.05


def test_quantized_forward_tracks_fp32_gemma(cpu_devices):
    cfg = get_config("tiny-gemma")
    params = init_params(cfg, jax.random.PRNGKey(3), jnp.float32)
    qparams = quantize_params(params)
    ref, got = _logit_agreement(cfg, params, qparams)
    agree = (ref.argmax(-1) == got.argmax(-1)).mean()
    assert agree > 0.9, f"argmax agreement too low: {agree}"


def test_engine_with_int8(cpu_devices):
    from p2p_llm_tunnel_tpu.engine.engine import EngineConfig, InferenceEngine

    eng = InferenceEngine(
        engine_cfg=EngineConfig(model="tiny", num_slots=2, max_seq=64,
                                dtype="float32", decode_steps=2, quant="int8")
    )
    assert isinstance(eng.params["blocks"]["wq"], QTensor)

    async def main():
        await eng.start()
        toks = []
        async for ev in eng.generate(list(b"quantized"), max_new_tokens=6,
                                     stop_ids=()):
            toks.append(ev.token_id)
        await eng.stop()
        return toks

    toks = asyncio.run(asyncio.wait_for(main(), 120))
    assert len(toks) == 6


def test_w8a8_mm_tracks_float():
    """act_quant=True path: dynamic int8 activations × int8 weights."""
    w = jax.random.normal(jax.random.PRNGKey(7), (64, 48), jnp.float32)
    x = jax.random.normal(jax.random.PRNGKey(8), (5, 64), jnp.float32)
    from p2p_llm_tunnel_tpu.models.quant import _quantize

    qt = _quantize(w, axis=0)
    got = np.asarray(mm(x, qt, act_quant=True))
    want = np.asarray(x) @ np.asarray(w)
    # two int8 quantizations compound: compare relative to magnitude
    denom = np.abs(want).mean() + 1e-6
    assert np.abs(got - want).mean() / denom < 0.05


def test_w8a8_head_matmul_tracks_float():
    from p2p_llm_tunnel_tpu.models.quant import _quantize, head_matmul

    embed = jax.random.normal(jax.random.PRNGKey(9), (96, 64), jnp.float32)
    x = jax.random.normal(jax.random.PRNGKey(10), (3, 64), jnp.float32)
    qt = _quantize(embed, axis=1)  # per-vocab-row, as quantize_params does
    got = np.asarray(head_matmul(x, qt, act_quant=True))
    want = np.asarray(x) @ np.asarray(embed).T
    denom = np.abs(want).mean() + 1e-6
    assert np.abs(got - want).mean() / denom < 0.05


def test_w8a8_prefill_tracks_fp32(cpu_devices):
    """Full-model forward with dynamic activation quant stays close enough
    for argmax agreement — the accuracy bar for using it in serving."""
    from dataclasses import replace

    cfg = get_config("tiny")
    params = init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    qparams = quantize_params(params)
    aq_cfg = replace(cfg, act_quant=True)
    tokens = jnp.arange(24)[None, :] % cfg.vocab_size
    valid = jnp.ones_like(tokens, bool)
    ref, _, _ = jax.jit(lambda p: prefill(cfg, p, tokens, valid))(params)
    got, _, _ = jax.jit(lambda p: prefill(aq_cfg, p, tokens, valid))(qparams)
    ref, got = np.asarray(ref), np.asarray(got)
    agree = (ref.argmax(-1) == got.argmax(-1)).mean()
    assert agree > 0.85, f"argmax agreement too low: {agree}"


def test_engine_with_w8a8(cpu_devices):
    from p2p_llm_tunnel_tpu.engine.engine import EngineConfig, InferenceEngine

    eng = InferenceEngine(
        engine_cfg=EngineConfig(model="tiny", num_slots=2, max_seq=64,
                                dtype="float32", decode_steps=2, quant="w8a8")
    )
    assert isinstance(eng.params["blocks"]["wq"], QTensor)
    assert eng.mcfg.act_quant

    async def main():
        await eng.start()
        toks = []
        async for ev in eng.generate(list(b"quantized"), max_new_tokens=6,
                                     stop_ids=()):
            toks.append(ev.token_id)
        await eng.stop()
        return toks

    toks = asyncio.run(asyncio.wait_for(main(), 120))
    assert len(toks) == 6


def test_init_params_quantized_single_jit(cpu_devices):
    """Whole-tree int8 init returns QTensor leaves with the right shapes."""
    from p2p_llm_tunnel_tpu.models.quant import init_params_quantized

    cfg = get_config("tiny")
    params = init_params_quantized(cfg, jax.random.PRNGKey(0))
    wq = params["blocks"]["wq"]
    assert isinstance(wq, QTensor)
    assert wq.q.dtype == jnp.int8
    assert wq.q.shape == (cfg.n_layers, cfg.dim, cfg.n_heads * cfg.head_dim)
    assert params["embed"].q.shape == (cfg.vocab_size, cfg.dim)


def test_engine_prefill_act_quant(cpu_devices):
    """prefill_act_quant: prefill runs W8A8, decode stays weight-only —
    generation must work end to end and the decode config stays unchanged."""
    from p2p_llm_tunnel_tpu.engine.engine import EngineConfig, InferenceEngine

    eng = InferenceEngine(
        engine_cfg=EngineConfig(model="tiny", num_slots=2, max_seq=64,
                                dtype="float32", decode_steps=2, quant="int8",
                                prefill_act_quant=True)
    )
    assert eng._prefill_mcfg.act_quant
    assert not eng.mcfg.act_quant

    async def main():
        await eng.start()
        toks = []
        async for ev in eng.generate(list(b"pf8"), max_new_tokens=5,
                                     stop_ids=()):
            toks.append(ev.token_id)
        await eng.stop()
        return toks

    toks = asyncio.run(asyncio.wait_for(main(), 120))
    assert len(toks) == 5
