"""Packed-int4 weight quantization: round-trip invariants, matmul
equivalence vs the explicit-dequant reference, kernel composition, and
decode token-identity (ISSUE 2 tentpole).

Deliberately NOT marked slow: tiny shapes only, so the int4 invariants run
in every `make test-fast` iteration (the engine-level e2e lives with the
other compile-heavy quant tests in test_quant.py).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from p2p_llm_tunnel_tpu.models.config import ModelConfig, get_config
from p2p_llm_tunnel_tpu.models.quant import (
    QTensor4,
    _dequant4,
    _quantize4,
    embed_lookup,
    head_matmul,
    mm,
    pack_int4,
    quantize_params_int4,
    unpack_int4,
)
from p2p_llm_tunnel_tpu.models.transformer import init_params, prefill


def test_pack_unpack_bit_exact():
    """Every nibble value in [-8, 7] survives pack→unpack on every axis."""
    rng = np.random.default_rng(0)
    v = rng.integers(-8, 8, (6, 10, 4)).astype(np.int8)
    for axis in (0, 1, 2, -1, -2, -3):
        if v.shape[axis] % 2:
            continue
        packed = pack_int4(jnp.asarray(v), axis=axis)
        assert packed.dtype == jnp.int8
        assert packed.shape[axis] == v.shape[axis] // 2
        out = np.asarray(unpack_int4(packed, axis=axis))
        np.testing.assert_array_equal(out, v)
    # The full nibble range, explicitly.
    edge = np.arange(-8, 8, dtype=np.int8)
    np.testing.assert_array_equal(
        np.asarray(unpack_int4(pack_int4(jnp.asarray(edge)))), edge
    )


@pytest.mark.parametrize("k", [33, 64, 128, 130, 256])
def test_quantize4_roundtrip_error_bounded(k):
    """Dequant error per group is bounded by scale/2 = absmax/14, across
    odd contracted dims (33), sub-group dims (64), exact fits (128/256),
    and group-boundary crossings (130)."""
    rng = np.random.default_rng(k)
    w = rng.standard_normal((k, 16)).astype(np.float32)
    qt = _quantize4(jnp.asarray(w), axis=-2, group_size=128)
    assert isinstance(qt, QTensor4) and qt.q.dtype == jnp.int8
    deq = np.asarray(_dequant4(qt, jnp.float32))
    assert deq.shape == (k, 16)  # logical shape restored, pad sliced off
    assert np.abs(deq - w).max() <= np.abs(w).max() / 7 + 1e-6


@pytest.mark.parametrize("k,group", [(33, 128), (64, 128), (130, 128),
                                     (256, 128), (96, 32)])
def test_mm_matches_explicit_dequant(k, group):
    """The fused mm path must equal x @ dequant(w) exactly — the fusion
    may never change the math, only where the bytes are read."""
    rng = np.random.default_rng(k + group)
    w = rng.standard_normal((k, 24)).astype(np.float32)
    x = jnp.asarray(rng.standard_normal((4, k)).astype(np.float32))
    qt = _quantize4(jnp.asarray(w), axis=-2, group_size=group)
    got = np.asarray(jax.jit(mm)(x, qt))
    want = np.asarray(x) @ np.asarray(_dequant4(qt, jnp.float32))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_embed_lookup_and_head_matmul_match_dequant():
    rng = np.random.default_rng(7)
    emb = rng.standard_normal((50, 130)).astype(np.float32)
    qe = _quantize4(jnp.asarray(emb), axis=-1, group_size=64)
    deq = np.asarray(_dequant4(qe, jnp.float32))
    toks = jnp.asarray(rng.integers(0, 50, (2, 7)))
    rows = np.asarray(embed_lookup(qe, toks, jnp.float32))
    np.testing.assert_allclose(rows, deq[np.asarray(toks)], rtol=1e-5,
                               atol=1e-6)
    x = jnp.asarray(rng.standard_normal((3, 130)).astype(np.float32))
    logits = np.asarray(head_matmul(x, qe))
    np.testing.assert_allclose(logits, np.asarray(x) @ deq.T, rtol=1e-4,
                               atol=1e-5)


def _dequant_tree(qparams):
    """QTensor4 tree -> plain bf16 tree: the unfused reference weights.

    bf16, not f32: the quantized serving path runs bf16 activations (the
    embed gather casts int->bfloat16, same as int8), and mm dequantizes
    into x.dtype — so the bit-identical reference is the bf16 dequant."""
    return jax.tree.map(
        lambda leaf: _dequant4(leaf, jnp.bfloat16)
        if isinstance(leaf, QTensor4) else leaf,
        qparams,
        is_leaf=lambda leaf: isinstance(leaf, QTensor4),
    )


def test_int4_prefill_tracks_fp32():
    """Full tiny forward through scanned QTensor4 blocks (the negative-axis
    aux must survive lax.scan's layer slicing) stays close to fp32."""
    cfg = get_config("tiny")
    params = init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    qparams = quantize_params_int4(params, group_size=32)
    tokens = jnp.arange(24)[None, :] % cfg.vocab_size
    valid = jnp.ones_like(tokens, bool)
    ref, _, _ = jax.jit(lambda p: prefill(cfg, p, tokens, valid))(params)
    got, _, _ = jax.jit(lambda p: prefill(cfg, p, tokens, valid))(qparams)
    ref, got = np.asarray(ref), np.asarray(got)
    # Random tiny weights are int4's WORST case (no structure for the
    # group scales to exploit; bf16 activations compound): measured ~33%
    # mean drift.  The numerics anchor is tests/test_golden_logits.py;
    # here we bound gross divergence and require the distributions to
    # stay strongly aligned — a conventions bug (wrong axis, wrong scale
    # grouping) decorrelates them entirely.
    denom = np.abs(ref).mean() + 1e-6
    assert np.abs(ref - got).mean() / denom < 0.6
    r = ref.reshape(-1, ref.shape[-1])
    g = got.reshape(-1, got.shape[-1])
    cos = (r * g).sum(-1) / (
        np.linalg.norm(r, axis=-1) * np.linalg.norm(g, axis=-1) + 1e-9
    )
    assert cos.min() > 0.75, cos.min()
    assert cos.mean() > 0.9, cos.mean()


def test_int4_decode_token_identical_to_dequant_reference():
    """ISSUE 2 acceptance: greedy decode with int4 weights must emit
    EXACTLY the tokens of the same int4 weights run through the unfused
    reference path (explicit dequant to plain fp32 arrays) — the packing
    is a storage format, not a numerics change."""
    from p2p_llm_tunnel_tpu.models.transformer import (
        decode_step, init_kv_cache, prefill_into_cache,
    )

    cfg = get_config("tiny")
    params = init_params(cfg, jax.random.PRNGKey(2), jnp.float32)
    qparams = quantize_params_int4(params, group_size=32)
    ref_params = _dequant_tree(qparams)
    prompt = jnp.asarray([[3, 1, 4, 1, 5, 9, 2, 6]])
    plen = prompt.shape[1]

    def run(p):
        cache = init_kv_cache(cfg, 2, 64, jnp.float32)
        last, cache = prefill_into_cache(
            cfg, p, prompt, jnp.array([plen]), cache, jnp.array([0])
        )
        toks = [int(np.asarray(last).argmax(-1)[0])]
        for i in range(12):
            step_tok = jnp.array([toks[-1], 0], jnp.int32)
            step_pos = jnp.array([plen + i, 0], jnp.int32)
            logits, cache = decode_step(cfg, p, cache, step_tok, step_pos)
            toks.append(int(np.asarray(logits).argmax(-1)[0]))
        return toks

    assert run(qparams) == run(ref_params)


def test_sgrid_int4_kernel_matches_einsum_oracle():
    """Interpret-mode oracle for the packed-int4-KV s-grid kernel: must
    equal einsum attention over the dequantized cache, per-slot frontiers
    included."""
    from p2p_llm_tunnel_tpu.ops.attention import cached_attention
    from p2p_llm_tunnel_tpu.ops.pallas_decode_attention import (
        flash_decode_attention_sgrid_int4,
    )

    rng = np.random.default_rng(0)
    b, s, h, kh, d = 3, 256, 4, 2, 128
    q = jnp.asarray(rng.standard_normal((b, 1, h, d)).astype(np.float32))
    kf = rng.standard_normal((b, s, kh, d)).astype(np.float32)
    vf = rng.standard_normal((b, s, kh, d)).astype(np.float32)
    pos = jnp.asarray([5, 130, 255], jnp.int32)

    def q4(x):
        amax = np.abs(x).max(-1, keepdims=True)
        scale = np.maximum(amax, 1e-8) / 7.0
        qv = np.clip(np.round(x / scale), -7, 7)
        return qv.astype(np.int8), scale

    k4, ks = q4(kf)
    v4, vs = q4(vf)
    ref = cached_attention(
        q, jnp.asarray(k4 * ks), jnp.asarray(v4 * vs), pos
    )
    got = flash_decode_attention_sgrid_int4(
        q,
        pack_int4(jnp.asarray(k4), axis=1),
        pack_int4(jnp.asarray(v4), axis=1),
        jnp.asarray(ks[..., 0]), jnp.asarray(vs[..., 0]),
        pos, interpret=True,
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(ref), rtol=2e-3, atol=2e-3
    )


def test_int4_weights_compose_with_sgrid_kv8_one_program():
    """ISSUE 2 acceptance: int4 weights + flash_sgrid + int8 KV in ONE
    decode program (interpret mode) match the einsum decode path on the
    same quantized weights and cache."""
    from dataclasses import replace

    from p2p_llm_tunnel_tpu.models.transformer import (
        decode_step, init_kv_cache, prefill_into_cache,
    )

    cfg = replace(
        get_config("tiny"),
        flash_decode=True, flash_sgrid=True, flash_interpret=True,
    )
    base = replace(cfg, flash_decode=False, flash_sgrid=False)
    params = quantize_params_int4(
        init_params(cfg, jax.random.PRNGKey(4), jnp.float32), group_size=32
    )
    prompt = jnp.asarray([[7, 2, 7, 1, 8, 2, 8, 1]])

    def run(c):
        cache = init_kv_cache(c, 2, 128, jnp.float32, quant=True)
        last, cache = prefill_into_cache(
            c, params, prompt, jnp.array([8]), cache, jnp.array([0])
        )
        logits, _ = decode_step(
            c, params, cache,
            jnp.array([int(np.asarray(last).argmax(-1)[0]), 0], jnp.int32),
            jnp.array([8, 0], jnp.int32),
            kv_view=128,
        )
        return np.asarray(logits)[0]

    fused = run(cfg)
    oracle = run(base)
    # bf16 activations (the int4 serving dtype): the two attention
    # implementations round differently at bf16 resolution (~0.8%); the
    # bound is a few bf16 ulps at |logits| ~ 2, and argmax must hold.
    np.testing.assert_allclose(fused, oracle, rtol=5e-2, atol=5e-2)
    assert fused.argmax() == oracle.argmax()


def test_int4_params_shard_over_tp_mesh(cpu_devices):
    """QTensor4 leaves get rank-congruent specs (scale takes the weight
    spec verbatim): int4 params place onto a tp mesh and the sharded
    forward matches the single-device one."""
    from p2p_llm_tunnel_tpu.parallel import make_mesh
    from p2p_llm_tunnel_tpu.parallel.sharding import shard_params

    cfg = get_config("tiny")
    params = init_params(cfg, jax.random.PRNGKey(5), jnp.float32)
    qparams = quantize_params_int4(params, group_size=32)
    tokens = jnp.arange(16)[None, :] % cfg.vocab_size
    valid = jnp.ones_like(tokens, bool)
    want, _, _ = jax.jit(lambda p: prefill(cfg, p, tokens, valid))(qparams)
    mesh = make_mesh(tp=2, dp=1)
    sharded = shard_params(qparams, cfg, mesh)
    got, _, _ = jax.jit(
        lambda p: prefill(cfg, p, tokens, valid, mesh=mesh)
    )(sharded)
    got = np.asarray(got, np.float32)
    want = np.asarray(want, np.float32)
    # bf16 activations + GSPMD's different reduction order: bound the
    # absolute drift (rtol is meaningless on near-zero logits).
    assert np.abs(got - want).max() < 0.06
    assert (got.argmax(-1) == want.argmax(-1)).mean() >= 0.9


def test_engine_config_rejects_odd_group_size():
    with pytest.raises(ValueError):
        _quantize4(jnp.ones((8, 8)), axis=-2, group_size=3)


def test_engine_adopts_injected_tree_group_size(cpu_devices):
    """An injected pre-quantized tree wins over the configured group size:
    otherwise _prefix_snapshot_meta would pin a group_size the served
    weights were never dequantized with, and a snapshot saved here would
    be accepted by a genuinely different engine."""
    from p2p_llm_tunnel_tpu.engine.engine import EngineConfig, InferenceEngine

    cfg = get_config("tiny")
    params = init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    qparams = quantize_params_int4(params, group_size=32)
    eng = InferenceEngine(
        model_cfg=cfg,
        engine_cfg=EngineConfig(model="tiny", num_slots=2, max_seq=64,
                                dtype="float32", quant="int4",
                                quant_group_size=64),
        params=qparams,
    )
    assert eng.params["blocks"]["wq"].group_size == 32
    # _prefix_snapshot_meta reads ecfg.quant_group_size; the adopted value
    # is what any snapshot pin will now record.
    assert eng.ecfg.quant_group_size == 32


def test_qtensor4_logical_shape():
    qt = _quantize4(jnp.ones((33, 5)), axis=-2, group_size=16)
    assert qt.shape == (33, 5)
    assert qt.in_dim == 33
    assert qt.q.shape == (24, 5)  # padded to 48, two per byte
    assert qt.scale.shape == (3, 5)


def test_convert_hf_int4_quantizes_with_group_scales():
    """checkpoint.convert_hf(quant='int4') returns QTensor4 leaves whose
    dequant matches quantizing the converted bf16 tree after the fact."""
    import sys
    import os
    import types

    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "scripts",
    ))
    from make_synth_hf_ckpt import fake_llama_state

    from p2p_llm_tunnel_tpu.models.checkpoint import convert_hf

    cfg = ModelConfig(name="synth", vocab_size=64, dim=32, n_layers=2,
                      n_heads=2, n_kv_heads=1, head_dim=16, ffn_dim=48)
    shape = types.SimpleNamespace(
        vocab_size=64, dim=32, n_layers=2, n_heads=2, n_kv_heads=1,
        head_dim=16, ffn_dim=48,
    )
    state = fake_llama_state(shape, 1)
    got = convert_hf("llama", state, cfg, jnp.float32, quant="int4",
                     group_size=16)
    assert isinstance(got["blocks"]["wq"], QTensor4)
    assert got["blocks"]["wq"].group_size == 16
    want = quantize_params_int4(
        convert_hf("llama", state, cfg, jnp.float32), group_size=16
    )
    np.testing.assert_array_equal(
        np.asarray(got["blocks"]["wq"].q), np.asarray(want["blocks"]["wq"].q)
    )
    np.testing.assert_allclose(
        np.asarray(got["embed"].scale), np.asarray(want["embed"].scale)
    )
