"""Ragged grouped flash-prefill kernel (ISSUE 15 tentpole): planner
properties, interpret-mode oracles vs the chunked path across
(group shapes × ragged lengths × kv quants × window/softcap), the int4
packed-write alignment property against the ISSUE 14 page/segment byte
boundaries, engine-level token-stream identity ragged-on vs ragged-off,
the float64 golden-logits anchor through the ragged kernel, the
warmup-plan collapse, and the cross-lowered grouped-launch evidence
(one tpu_custom_call per layer per group — utils/hlo.py).
"""

import asyncio
import os
import sys
import types
from dataclasses import replace

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from p2p_llm_tunnel_tpu.models.config import ModelConfig, get_config
from p2p_llm_tunnel_tpu.models.quant import pack_int4, unpack_int4
from p2p_llm_tunnel_tpu.models.transformer import (
    _quant_kv,
    _quant_kv4,
    chunk_prefill_into_cache,
    init_kv_cache,
    init_params,
    ragged_prefill_into_cache,
)
from p2p_llm_tunnel_tpu.ops.attention import history_attention
from p2p_llm_tunnel_tpu.ops.pallas_prefill_attention import (
    plan_ragged_group,
    ragged_prefill_attention,
)
from p2p_llm_tunnel_tpu.ops.rope import apply_rope

THETA = 10000.0

#: Ragged group exercising every descriptor shape at once: history + tail,
#: zero-history, multi-block odd-length tail, exactly-one-block tail.
ENTRIES = [(0, 32, 20), (1, 0, 7), (2, 16, 33), (3, 0, 16)]


# ---------------------------------------------------------------------------
# planner properties (fast tier)
# ---------------------------------------------------------------------------

def test_plan_ragged_group_packs_blocks_and_descriptors():
    slot_of, start_of, qoff_of, qlen_of, base_of, offs = plan_ragged_group(
        ENTRIES, 16, 128, scratch_slot=9
    )
    # Rows land at block-aligned flat offsets in order, no overlap.
    assert offs == [0, 32, 48, 96]
    # Row 2 (len 33) owns blocks 3..5, base pointing at its first block.
    assert list(slot_of[3:6]) == [2, 2, 2]
    assert list(qoff_of[3:6]) == [0, 16, 32]
    assert list(base_of[3:6]) == [3, 3, 3]
    assert list(qlen_of[3:6]) == [33, 33, 33]
    # Pad blocks: scratch slot, zero length, self-based (masking to zero).
    assert slot_of[-1] == 9 and qlen_of[-1] == 0 and base_of[-1] == 7


def test_plan_rejects_misaligned_start_and_overflow():
    # The ISSUE 14 alignment contract: starts must be block multiples —
    # an odd/misaligned start would shear the cache-append block maps
    # (and, packed int4, corrupt a neighbour's nibble).
    with pytest.raises(ValueError, match="multiple of the q-block"):
        plan_ragged_group([(0, 13, 8)], 16, 64, scratch_slot=1)
    with pytest.raises(ValueError, match="overflows"):
        plan_ragged_group([(0, 0, 60), (1, 0, 60)], 16, 96, scratch_slot=2)
    with pytest.raises(ValueError, match="tail_len"):
        plan_ragged_group([(0, 0, 0)], 16, 64, scratch_slot=1)


def test_kernel_rejects_odd_block_under_int4():
    l, b, s, kh, d = 1, 2, 64, 2, 32
    kc = jnp.zeros((l, b, s // 2, kh, d), jnp.int8)
    sc = jnp.zeros((l, b, s, kh), jnp.float32)
    nqb = 2
    desc = jnp.zeros((nqb,), jnp.int32)
    with pytest.raises(ValueError, match="even block_q"):
        ragged_prefill_attention(
            jnp.zeros((2 * 9, 4, d), jnp.float32),
            jnp.zeros((2 * 9, kh, d), jnp.float32),
            jnp.zeros((2 * 9, kh, d), jnp.float32),
            kc, kc, sc, sc, desc, desc, desc, desc,
            jnp.asarray(0), block_q=9, rope_theta=THETA, kv_quant="int4",
            interpret=True,
        )


# ---------------------------------------------------------------------------
# kernel-level oracle: rope → quant → append → history_attention (slow)
# ---------------------------------------------------------------------------

def _kernel_case(kv_quant, window=None, softcap=None, seed=0, s=128, bq=16,
                 tot=128):
    """Run the ragged kernel over ENTRIES[:3] and return everything the
    oracle checks need."""
    rng = np.random.default_rng(seed)
    l, b, kh, g, d = 2, 4, 2, 2, 32
    h = kh * g
    layer = 1
    entries = ENTRIES[:3]
    slot_of, start_of, qoff_of, qlen_of, base_of, offs = plan_ragged_group(
        entries, bq, tot, scratch_slot=3
    )
    hist_k = rng.standard_normal((l, b, s, kh, d)).astype(np.float32)
    hist_v = rng.standard_normal((l, b, s, kh, d)).astype(np.float32)
    if kv_quant is None:
        kc, vc = jnp.asarray(hist_k), jnp.asarray(hist_v)
        ksc = vsc = None
    else:
        qfn = _quant_kv4 if kv_quant == "int4" else _quant_kv
        kq, ks = qfn(jnp.asarray(hist_k))
        vq, vs = qfn(jnp.asarray(hist_v))
        if kv_quant == "int4":
            kc, vc = pack_int4(kq, axis=2), pack_int4(vq, axis=2)
        else:
            kc, vc = kq, vq
        ksc, vsc = ks, vs
    q = np.zeros((tot, h, d), np.float32)
    kn = np.zeros((tot, kh, d), np.float32)
    vn = np.zeros((tot, kh, d), np.float32)
    for (slot, start, ln), off in zip(entries, offs):
        q[off:off + ln] = rng.standard_normal((ln, h, d))
        kn[off:off + ln] = rng.standard_normal((ln, kh, d))
        vn[off:off + ln] = rng.standard_normal((ln, kh, d))
    outs = ragged_prefill_attention(
        jnp.asarray(q), jnp.asarray(kn), jnp.asarray(vn),
        kc, vc, ksc, vsc,
        jnp.asarray(slot_of), jnp.asarray(start_of), jnp.asarray(qoff_of),
        jnp.asarray(base_of), jnp.asarray(layer),
        block_q=bq, rope_theta=THETA, kv_quant=kv_quant,
        window=window, softcap=softcap, interpret=True,
    )
    return (entries, offs, layer, (hist_k, hist_v), (q, kn, vn),
            (kc, vc, ksc, vsc), outs)


def _oracle_row(kv_quant, layer, slot, start, ln, off, hists, news,
                window, softcap):
    """Per-row reference: rope at global positions, quantize-roundtrip
    through the cache precision, scatter, attend via history_attention —
    exactly what chunk_prefill_into_cache composes."""
    hist_k, hist_v = hists
    q, kn, vn = news
    pos = start + np.arange(ln)
    q_r = apply_rope(jnp.asarray(q[off:off + ln])[None],
                     jnp.asarray(pos)[None], THETA)
    kn_r = apply_rope(jnp.asarray(kn[off:off + ln])[None],
                      jnp.asarray(pos)[None], THETA)[0]
    vn_r = jnp.asarray(vn[off:off + ln])
    kc_l = jnp.asarray(hist_k)[layer, slot]
    vc_l = jnp.asarray(hist_v)[layer, slot]
    if kv_quant is None:
        kd = kc_l.at[pos].set(kn_r)
        vd = vc_l.at[pos].set(vn_r)
    else:
        qfn = _quant_kv4 if kv_quant == "int4" else _quant_kv
        hq_k, hs_k = qfn(kc_l)
        hq_v, hs_v = qfn(vc_l)
        nq_k, ns_k = qfn(kn_r)
        nq_v, ns_v = qfn(vn_r)
        kd = (hq_k.astype(jnp.float32) * hs_k[..., None]).at[pos].set(
            nq_k.astype(jnp.float32) * ns_k[..., None])
        vd = (hq_v.astype(jnp.float32) * hs_v[..., None]).at[pos].set(
            nq_v.astype(jnp.float32) * ns_v[..., None])
    want = history_attention(
        q_r, kd[None], vd[None], jnp.asarray([start]),
        window=window, softcap=softcap,
    )[0]
    return np.asarray(want), kn_r


@pytest.mark.parametrize("kv_quant", [None, "int8", "int4"])
def test_ragged_kernel_matches_history_attention_oracle(kv_quant):
    """Fast-tier core oracle: one interpret run covering history + tail,
    zero-history, and multi-block ragged rows in ONE grouped launch."""
    entries, offs, layer, hists, news, _caches, outs = _kernel_case(kv_quant)
    attn = np.asarray(outs[0])
    for (slot, start, ln), off in zip(entries, offs):
        want, _ = _oracle_row(kv_quant, layer, slot, start, ln, off,
                              hists, news, None, None)
        np.testing.assert_allclose(
            attn[off:off + ln], want, rtol=2e-4, atol=2e-4,
            err_msg=f"slot={slot} kv={kv_quant}",
        )


@pytest.mark.slow
@pytest.mark.parametrize("kv_quant", [None, "int8", "int4"])
@pytest.mark.parametrize("kw", [dict(window=48), dict(softcap=20.0)])
@pytest.mark.parametrize("s", [128, 512])
def test_ragged_kernel_oracle_windows_softcap_multiblock(kv_quant, kw, s):
    """s=512 exercises multi-block history with the frontier clamp; the
    window/softcap variants pin the masking/score paths."""
    entries, offs, layer, hists, news, _caches, outs = _kernel_case(
        kv_quant, s=s, seed=3, **kw
    )
    attn = np.asarray(outs[0])
    for (slot, start, ln), off in zip(entries, offs):
        want, _ = _oracle_row(
            kv_quant, layer, slot, start, ln, off, hists, news,
            kw.get("window"), kw.get("softcap"),
        )
        np.testing.assert_allclose(
            attn[off:off + ln], want, rtol=2e-4, atol=2e-4,
            err_msg=f"slot={slot} kv={kv_quant} s={s} {kw}",
        )


@pytest.mark.parametrize("kv_quant", ["int8", "int4"])
def test_ragged_append_bytes_exact_and_page_aligned(kv_quant):
    """The int4 packed-write alignment property (ISSUE 14/15): the
    grouped append lands the EXACT bytes the chunk path's quantize +
    pack_int4 scatter would, on whole-byte page/segment boundaries —
    other slots, other layers, and each row's history region untouched.
    Bit-exact: rope feeds round(), and the kernel reproduces apply_rope's
    expression graph precisely so the nibble never flips."""
    entries, offs, layer, hists, news, caches, outs = _kernel_case(kv_quant)
    _attn, kc2, _vc2, ks2, _vs2 = outs
    kc0 = caches[0]
    np.testing.assert_array_equal(np.asarray(kc2[0]), np.asarray(kc0[0]))
    for (slot, start, ln), off in zip(entries, offs):
        _, kn_r = _oracle_row(kv_quant, layer, slot, start, ln, off,
                              hists, news, None, None)
        qfn = _quant_kv4 if kv_quant == "int4" else _quant_kv
        nq_k, ns_k = qfn(kn_r)
        vals = np.asarray(kc2)[layer, slot]
        hist0 = np.asarray(kc0)[layer, slot]
        if kv_quant == "int4":
            vals = np.asarray(unpack_int4(jnp.asarray(vals), axis=0))
            hist0 = np.asarray(unpack_int4(jnp.asarray(hist0), axis=0))
        np.testing.assert_array_equal(vals[start:start + ln],
                                      np.asarray(nq_k))
        np.testing.assert_array_equal(vals[:start], hist0[:start])
        np.testing.assert_allclose(
            np.asarray(ks2)[layer, slot, start:start + ln],
            np.asarray(ns_k), rtol=1e-6,
        )


# ---------------------------------------------------------------------------
# transformer-level parity vs chunk_prefill_into_cache (slow)
# ---------------------------------------------------------------------------

@pytest.mark.slow
@pytest.mark.parametrize("kv_quant", [False, "int8", "int4"])
def test_ragged_prefill_matches_chunk_prefill(kv_quant):
    """Full-model parity: identical history (written by the chunk path),
    then the SAME ragged tails through both programs — last-token logits
    agree (argmax identical), quantized cache bytes agree to at most an
    ulp-flip of round() (the two whole-layer programs fuse differently),
    and history regions stay untouched."""
    cfg = replace(get_config("tiny", vocab_size=64), flash_interpret=True)
    params = init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    s = 128
    cache0 = init_kv_cache(cfg, 5, s, jnp.float32, quant=kv_quant)
    rng = np.random.default_rng(0)
    jit_chunk = jax.jit(
        chunk_prefill_into_cache,
        static_argnames=("cfg", "kv_view", "return_all_logits"),
    )
    # Shared history via the chunk path.
    hist = {0: 32, 2: 16}
    tk = np.zeros((2, 32), np.int32)
    ln = np.zeros((2,), np.int32)
    sl = np.zeros((2,), np.int32)
    for i, (slot, n) in enumerate(hist.items()):
        tk[i, :n] = rng.integers(1, 60, size=n)
        ln[i] = n
        sl[i] = slot
    _, cache = jit_chunk(
        cfg=cfg, params=params, tokens=jnp.asarray(tk),
        lengths=jnp.asarray(ln), starts=jnp.zeros((2,), jnp.int32),
        kv_cache=cache0, slots=jnp.asarray(sl), kv_view=s,
    )
    tails = {slot: rng.integers(1, 60, size=n).tolist()
             for (slot, _st, n) in ENTRIES}
    # Chunked reference: one padded-bucket call.
    tb = 48
    tk = np.zeros((4, tb), np.int32)
    ln = np.zeros((4,), np.int32)
    st = np.zeros((4,), np.int32)
    sl = np.zeros((4,), np.int32)
    for i, (slot, start, n) in enumerate(ENTRIES):
        tk[i, :n] = tails[slot]
        ln[i] = n
        st[i] = start
        sl[i] = slot
    last_c, cache_c = jit_chunk(
        cfg=cfg, params=params, tokens=jnp.asarray(tk),
        lengths=jnp.asarray(ln), starts=jnp.asarray(st),
        kv_cache=jax.tree.map(jnp.copy, cache), slots=jnp.asarray(sl),
        kv_view=s,
    )
    # Ragged path: same rows, flat-packed.
    bq, tot = 16, 112
    slot_of, start_of, qoff_of, qlen_of, base_of, offs = plan_ragged_group(
        ENTRIES, bq, tot, scratch_slot=4
    )
    flat = np.zeros((tot,), np.int32)
    samp_idx = np.zeros((4,), np.int32)
    for i, ((slot, start, n), off) in enumerate(zip(ENTRIES, offs)):
        flat[off:off + n] = tails[slot]
        samp_idx[i] = off + n - 1
    jit_ragged = jax.jit(
        ragged_prefill_into_cache,
        static_argnames=("cfg", "block_q", "return_all_logits",
                         "interpret"),
    )
    last_r, cache_r = jit_ragged(
        cfg=cfg, params=params, tokens=jnp.asarray(flat),
        slot_of=jnp.asarray(slot_of), start_of=jnp.asarray(start_of),
        qoff_of=jnp.asarray(qoff_of),
        base_of=jnp.asarray(base_of), sample_idx=jnp.asarray(samp_idx),
        kv_cache=jax.tree.map(jnp.copy, cache), block_q=bq,
    )
    np.testing.assert_allclose(np.asarray(last_r), np.asarray(last_c),
                               rtol=2e-4, atol=2e-4)
    assert (np.asarray(last_r).argmax(-1)
            == np.asarray(last_c).argmax(-1)).all()
    for slot, start, n in ENTRIES:
        for key in cache_r:
            a = np.asarray(cache_r[key])[:, slot]
            b = np.asarray(cache_c[key])[:, slot]
            h0 = np.asarray(cache[key])[:, slot]
            if key in ("k", "v") and kv_quant == "int4":
                a = np.asarray(unpack_int4(jnp.asarray(a), axis=1))
                b = np.asarray(unpack_int4(jnp.asarray(b), axis=1))
                h0 = np.asarray(unpack_int4(jnp.asarray(h0), axis=1))
            if key in ("k", "v") and kv_quant in ("int8", "int4"):
                reg_a = a[:, start:start + n].astype(np.int32)
                reg_b = b[:, start:start + n].astype(np.int32)
                # ulp-flip budget: the two programs' rope fuses
                # differently, so round() may flip on exact halves —
                # never by more than one step, never often.
                assert np.abs(reg_a - reg_b).max() <= 1
                assert np.mean(reg_a != reg_b) < 0.01
            else:
                np.testing.assert_allclose(
                    a[:, start:start + n], b[:, start:start + n],
                    rtol=1e-5, atol=1e-5,
                )
            np.testing.assert_array_equal(a[:, :start], h0[:, :start])


# ---------------------------------------------------------------------------
# engine-level token-stream identity (ISSUE 15 acceptance; slow)
# ---------------------------------------------------------------------------

async def _engine_stream(kv_quant, ragged, prompts):
    from p2p_llm_tunnel_tpu.engine.engine import EngineConfig, InferenceEngine
    from p2p_llm_tunnel_tpu.engine.tokenizer import ByteTokenizer

    tok = ByteTokenizer()
    eng = InferenceEngine(
        engine_cfg=EngineConfig(
            model="tiny", num_slots=4, max_seq=256, dtype="float32",
            decode_steps=4, kv_quant=kv_quant, mux=True,
            prefix_cache=True, ragged_prefill=ragged, seed=7,
        ),
        tokenizer=tok,
    )
    assert eng.ecfg.ragged_prefill == ragged, eng.config_fences

    async def collect(p):
        out = []
        async for ev in eng.generate(p, max_new_tokens=8, stop_ids=()):
            out.append(ev.token_id)
        return out

    await eng.start()
    outs = await asyncio.gather(*(collect(p) for p in prompts))
    # A prefix-hit tail after the pool is warm: the cached-wave route.
    outs.append(await collect(prompts[0][:40] + [99, 98, 97]))
    await eng.stop()
    return outs


@pytest.mark.slow
@pytest.mark.parametrize("kv_quant", ["none", "int8", "int4"])
def test_engine_stream_byte_identical_ragged_on_vs_off(kv_quant):
    """ISSUE 15 acceptance: under mux + prefix-grouped admission, the
    ragged path's token streams are identical to the chunked path's at
    every kv_quant — shared-prefix herd, multi-segment prompt, short
    prompt, and a warm prefix-hit tail all covered (TIE_FREE_SEED family:
    seed 7 keeps greedy argmax tie-free, see test_fused_decode_layer)."""
    shared = list(range(1, 81))
    prompts = [shared + [100 + i] for i in range(3)]
    prompts.append(list(range(1, 150)))  # multi-segment (149 > chunk 128)
    prompts.append([5, 4, 3])
    a = asyncio.run(_engine_stream(kv_quant, False, prompts))
    b = asyncio.run(_engine_stream(kv_quant, True, prompts))
    assert all(len(x) == 8 for x in a)
    assert a == b, f"ragged stream diverged under kv_quant={kv_quant}"


def test_engine_fences_ragged_on_misaligned_geometry():
    """A prefill_chunk that shares no power-of-2 block >= 8 with the page
    size cannot align the grouped cache-append blocks — the engine fences
    the knob OFF and records why, instead of corrupting at serve time."""
    from p2p_llm_tunnel_tpu.engine.engine import EngineConfig, InferenceEngine
    from p2p_llm_tunnel_tpu.engine.tokenizer import ByteTokenizer

    eng = InferenceEngine(
        engine_cfg=EngineConfig(
            model="tiny", num_slots=2, max_seq=128, dtype="float32",
            prefill_chunk=100, ragged_prefill=True,
        ),
        tokenizer=ByteTokenizer(),
    )
    assert eng.ecfg.ragged_prefill is False
    assert any(f["knob"] == "ragged_prefill" for f in eng.config_fences)


# ---------------------------------------------------------------------------
# warmup-plan collapse (ISSUE 15 acceptance)
# ---------------------------------------------------------------------------

def _plan(ragged):
    from p2p_llm_tunnel_tpu.engine.engine import EngineConfig, InferenceEngine
    from p2p_llm_tunnel_tpu.engine.tokenizer import ByteTokenizer

    eng = InferenceEngine(
        engine_cfg=EngineConfig(
            model="tiny", num_slots=8, max_seq=512, dtype="float32",
            mux=True, prefix_cache=True, ragged_prefill=ragged,
        ),
        tokenizer=ByteTokenizer(),
    )
    return eng.warmup_plan()


def test_warmup_plan_collapses_2x_on_mux_hero_shape():
    """ISSUE 15 acceptance: on the mux hero shape (prefix-grouped
    admission, defaulted segment width, max_seq 512) the ragged config's
    warmup program count is >= 2x smaller — the whole chunk[t, view]
    family becomes one ragged[tot] program, and the decode view set stays
    identical (raggedness must not bill decode)."""
    off = _plan(False)
    on = _plan(True)
    assert [p for p in off if p[0] == "decode"] == [
        p for p in on if p[0] == "decode"
    ]
    assert sum(1 for p in off if p[0] == "chunk") >= 8
    assert [p for p in on if p[0] not in ("decode",)] == [("ragged", (1024,))]
    assert len(off) >= 2 * len(on), (off, on)


# ---------------------------------------------------------------------------
# float64 golden-logits anchor through the ragged kernel (slow)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_ragged_prefill_matches_golden_logits():
    """Teacher-forced prefill of the committed float64 anchor through the
    ragged kernel (one ragged row, full-position logits): the grouped
    rope / append / prefix+tail attention math is pinned to an
    implementation that shares no code with it."""
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "scripts",
    ))
    from make_synth_hf_ckpt import fake_llama_state

    from p2p_llm_tunnel_tpu.models.checkpoint import convert_hf

    fx = np.load(os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "golden",
        "synth_llama_logits.npz",
    ))
    vocab, dim, layers, heads, kv_heads, head_dim, ffn, seed = fx["meta"]
    cfg = ModelConfig(
        name="synth-golden", vocab_size=int(vocab), dim=int(dim),
        n_layers=int(layers), n_heads=int(heads), n_kv_heads=int(kv_heads),
        head_dim=int(head_dim), ffn_dim=int(ffn),
        rope_theta=10000.0, norm_eps=1e-5, flash_interpret=True,
    )
    shape = types.SimpleNamespace(
        vocab_size=int(vocab), dim=int(dim), n_layers=int(layers),
        n_heads=int(heads), n_kv_heads=int(kv_heads),
        head_dim=int(head_dim), ffn_dim=int(ffn),
    )
    params = convert_hf(
        "llama", fake_llama_state(shape, int(seed)), cfg, jnp.float32
    )
    tokens = fx["tokens"]
    want = fx["logits"]
    n = len(tokens)
    bq = 16
    tot = -(-n // bq) * bq
    cache = init_kv_cache(cfg, 2, max(tot, 64), jnp.float32)
    slot_of, start_of, qoff_of, qlen_of, base_of, offs = plan_ragged_group(
        [(0, 0, n)], bq, tot, scratch_slot=1
    )
    flat = np.zeros((tot,), np.int32)
    flat[:n] = tokens
    logits, _cache = jax.jit(
        ragged_prefill_into_cache,
        static_argnames=("cfg", "block_q", "return_all_logits",
                         "interpret"),
    )(
        cfg=cfg, params=params, tokens=jnp.asarray(flat),
        slot_of=jnp.asarray(slot_of), start_of=jnp.asarray(start_of),
        qoff_of=jnp.asarray(qoff_of),
        base_of=jnp.asarray(base_of),
        sample_idx=jnp.zeros((1,), jnp.int32),
        kv_cache=cache, block_q=bq, return_all_logits=True,
    )
    got = np.asarray(logits, np.float32)[:n]
    # fp32 anchor family (test_golden_logits: 1e-5/1e-4) with headroom
    # for the online-softmax accumulation order.
    np.testing.assert_allclose(got, want, atol=1e-4, rtol=1e-3)
    assert (got.argmax(-1) == want.argmax(-1)).all()


# ---------------------------------------------------------------------------
# off-chip grouped-launch evidence (utils/hlo.py; slow)
# ---------------------------------------------------------------------------

#: TPU-tileable config: head_dim 128 so the REAL (non-interpret) kernel
#: cross-lowers for the TPU platform from this CPU host.
TILE_CFG = ModelConfig(
    name="tiny128", vocab_size=256, dim=128, n_layers=2, n_heads=4,
    n_kv_heads=2, head_dim=128, ffn_dim=256,
)


@pytest.mark.slow
def test_ragged_group_cross_lowers_to_one_pallas_call_per_layer():
    """ISSUE 15 acceptance: the TPU-lowered ragged program's layer body
    carries exactly ONE tpu_custom_call for the whole GROUP — where the
    bucketed path launches one chunk program per (tail, view) pair, the
    grouped kernel is a single launch per layer regardless of how many
    rows ride it (the PR 4 launch-arithmetic technique on prefill)."""
    from p2p_llm_tunnel_tpu.utils.hlo import decode_launch_report

    params = init_params(TILE_CFG, jax.random.PRNGKey(0), jnp.float32)
    cache = init_kv_cache(TILE_CFG, 5, 256, jnp.float32)
    bq, tot = 16, 160
    entries = [(0, 32, 20), (1, 0, 33), (2, 16, 16), (3, 0, 40)]
    slot_of, start_of, qoff_of, qlen_of, base_of, _ = plan_ragged_group(
        entries, bq, tot, scratch_slot=4
    )
    jitted = jax.jit(
        ragged_prefill_into_cache,
        static_argnames=("cfg", "block_q", "return_all_logits",
                         "interpret"),
    )
    report = decode_launch_report(
        jitted,
        cfg=TILE_CFG, params=params, tokens=jnp.zeros((tot,), jnp.int32),
        slot_of=jnp.asarray(slot_of), start_of=jnp.asarray(start_of),
        qoff_of=jnp.asarray(qoff_of),
        base_of=jnp.asarray(base_of),
        sample_idx=jnp.zeros((4,), jnp.int32),
        kv_cache=cache, block_q=bq, interpret=False,
    )
    assert report is not None, "TPU cross-lowering failed"
    assert report["layer_body_pallas"] == 1, (
        "the grouped prefill layer is not ONE pallas call"
    )
