"""Opt-in end-to-end smoke test with a REAL pretrained checkpoint.

VERDICT r3 item 8: the HF-conversion path (models/checkpoint.py convert_hf)
is exercised by synthetic trees in test_checkpoint.py; this test closes the
loop with actual pretrained weights — load → quantize → serve → stream
coherent greedy text through the tunnel via /v1/chat/completions.

Opt-in because the CI image has no model weights and no network egress:
set ``TUNNEL_HF_CKPT`` to a local HuggingFace checkpoint directory (config
+ safetensors + tokenizer) of a llama- or gemma2-family model, e.g.

    TUNNEL_HF_CKPT=/models/Llama-3.2-1B TUNNEL_HF_FAMILY=llama \\
        python -m pytest tests/test_real_checkpoint.py -v
"""

import asyncio
import json
import os

import pytest

CKPT = os.environ.get("TUNNEL_HF_CKPT")
FAMILY = os.environ.get("TUNNEL_HF_FAMILY", "llama")

pytestmark = pytest.mark.skipif(
    not CKPT or not os.path.isdir(CKPT),
    reason="TUNNEL_HF_CKPT not set / not a directory (opt-in weights test)",
)


def _load_hf_params_and_cfg():
    """Read an HF checkpoint directory into (ModelConfig, params, tokenizer)
    without network access."""
    import numpy as np

    from p2p_llm_tunnel_tpu.engine.tokenizer import HFTokenizer
    from p2p_llm_tunnel_tpu.models.checkpoint import convert_hf
    from p2p_llm_tunnel_tpu.models.config import ModelConfig

    with open(os.path.join(CKPT, "config.json")) as f:
        hf = json.load(f)
    kwargs = dict(
        name=os.path.basename(CKPT.rstrip("/")),
        vocab_size=hf["vocab_size"],
        dim=hf["hidden_size"],
        n_layers=hf["num_hidden_layers"],
        n_heads=hf["num_attention_heads"],
        n_kv_heads=hf.get("num_key_value_heads", hf["num_attention_heads"]),
        head_dim=hf.get(
            "head_dim", hf["hidden_size"] // hf["num_attention_heads"]
        ),
        ffn_dim=hf["intermediate_size"],
        rope_theta=hf.get("rope_theta", 10000.0),
        norm_eps=hf.get("rms_norm_eps", 1e-5),
        tie_embeddings=hf.get("tie_word_embeddings", False),
    )
    if FAMILY == "gemma2":
        # gemma-2's architecture knobs do NOT live at llama defaults; a
        # config without them silently runs the wrong forward pass.
        kwargs.update(
            act="gelu",
            post_norms=True,
            attn_softcap=hf.get("attn_logit_softcapping", 50.0),
            logit_softcap=hf.get("final_logit_softcapping", 30.0),
            sliding_window=hf.get("sliding_window", 4096),
            embed_scale=True,
            query_scale=hf.get("query_pre_attn_scalar", 256) ** -0.5,
            norm_eps=hf.get("rms_norm_eps", 1e-6),
            tie_embeddings=True,
        )
    cfg = ModelConfig(**kwargs)

    state = {}
    try:
        from safetensors import safe_open

        for fn in sorted(os.listdir(CKPT)):
            if fn.endswith(".safetensors"):
                with safe_open(os.path.join(CKPT, fn), framework="np") as f:
                    for k in f.keys():
                        state[k] = f.get_tensor(k)
    except ImportError:
        import torch

        for fn in sorted(os.listdir(CKPT)):
            if fn.endswith(".bin"):
                sd = torch.load(
                    os.path.join(CKPT, fn), map_location="cpu",
                    weights_only=True,
                )
                for k, v in sd.items():
                    state[k] = v.to(torch.float32).numpy()
    if not state:
        pytest.skip("no safetensors/bin weight files found in TUNNEL_HF_CKPT")

    params = convert_hf(FAMILY, state, cfg)
    tok = HFTokenizer(CKPT)
    return cfg, params, tok


def test_real_checkpoint_streams_coherent_text():
    from p2p_llm_tunnel_tpu.endpoints.http11 import http_request
    from p2p_llm_tunnel_tpu.endpoints.proxy import run_proxy
    from p2p_llm_tunnel_tpu.endpoints.serve import run_serve
    from p2p_llm_tunnel_tpu.engine.api import engine_backend
    from p2p_llm_tunnel_tpu.engine.engine import EngineConfig, InferenceEngine
    from p2p_llm_tunnel_tpu.transport.loopback import loopback_pair

    cfg, params, tok = _load_hf_params_and_cfg()

    async def main():
        engine = InferenceEngine(
            model_cfg=cfg,
            engine_cfg=EngineConfig(
                model=cfg.name, num_slots=2, max_seq=256,
                decode_steps=4, quant="int8",
            ),
            params=params,
            tokenizer=tok,
        )
        await engine.start()
        serve_ch, proxy_ch = loopback_pair()
        serve_task = asyncio.create_task(
            run_serve(serve_ch, backend=engine_backend(engine, cfg.name))
        )
        ready: asyncio.Future = asyncio.get_running_loop().create_future()
        proxy_task = asyncio.create_task(
            run_proxy(proxy_ch, "127.0.0.1", 0, ready=ready)
        )
        port = await asyncio.wait_for(ready, 30.0)
        try:
            resp = await http_request(
                "POST",
                f"http://127.0.0.1:{port}/v1/chat/completions",
                {"content-type": "application/json"},
                json.dumps(
                    {
                        "messages": [
                            {"role": "user", "content": "The capital of France is"}
                        ],
                        "max_tokens": 12,
                        "temperature": 0.0,
                        "stream": False,
                    }
                ).encode(),
                timeout=600.0,
            )
            assert resp.status == 200
            body = json.loads(
                b"".join([c async for c in resp.iter_chunks()])
            )
            text = body["choices"][0]["message"]["content"]
            if os.environ.get("TUNNEL_HF_SYNTH") == "1":
                # Synthetic real-format checkpoint
                # (scripts/make_synth_hf_ckpt.py): random weights cannot
                # clear a LANGUAGE bar, so assert the mechanical
                # invariants the formats path must uphold.  Greedy decode
                # under random weights CAN hit </s> at any step (and the
                # exact ids shift with tokenizers/numpy versions), so
                # accept either finish reason and any non-zero token
                # count within budget.
                finish = body["choices"][0]["finish_reason"]
                assert finish in ("length", "stop")
                # An immediate greedy </s> legitimately yields empty text
                # (skip_special_tokens) — require text only when the run
                # went the distance.
                assert text or finish == "stop", (
                    "no text decoded from synthetic model"
                )
                assert 1 <= body["usage"]["completion_tokens"] <= 12
                # The prompt must have gone through the tokenizer's OWN
                # chat template: the templated rendering strictly extends
                # the raw prompt with role/eos special tokens.
                raw_len = len(tok.encode("The capital of France is"))
                assert body["usage"]["prompt_tokens"] > raw_len, (
                    "prompt_tokens suggests apply_chat_template was "
                    "bypassed"
                )
                print(f"synthetic-ckpt output: {text!r}")
            else:
                # Coherence bar: real weights under greedy decode must
                # produce language, not noise.  Any competent base model
                # continues the prompt with "Paris"; failing that,
                # require the output to be mostly letters/spaces (catches
                # garbage like "aQz!!" that a broken conversion
                # produces).
                assert text.strip(), "model produced no text"
                wordish = (
                    sum(c.isalpha() or c.isspace() for c in text)
                    / len(text)
                )
                assert "paris" in text.lower() or wordish > 0.8, (
                    f"output fails the coherence bar: {text!r}"
                )
                print(f"model output: {text!r}")
        finally:
            serve_task.cancel()
            proxy_task.cancel()
            for t in (serve_task, proxy_task):
                try:
                    await t
                except (asyncio.CancelledError, RuntimeError):
                    pass
            await engine.stop()

    asyncio.run(asyncio.wait_for(main(), 1200))
