"""Failure/recovery: supervised reconnects and in-flight failover.

Exercises SURVEY.md §3.5 — transport dies → endpoints raise → run_with_retry
re-runs connect() → fresh channel, fresh handshake — which even the
reference only covers manually (its scripts never fault-inject).  ISSUE 8
adds the multi-peer recovery contract: killing one serve peer of a fabric
mid-herd re-dispatches every not-yet-streaming request to a survivor
(zero client-visible failures) and ends already-streaming requests with a
TYPED ``peer_lost`` finish, deterministically under the seeded chaos kill
schedule.
"""

import asyncio
import json
import os
import random

import pytest

from p2p_llm_tunnel_tpu import cli
from p2p_llm_tunnel_tpu.endpoints.http11 import http_request
from p2p_llm_tunnel_tpu.endpoints.proxy import (
    ProxyState,
    run_proxy,
    run_proxy_fabric,
)
from p2p_llm_tunnel_tpu.endpoints.serve import run_serve
from p2p_llm_tunnel_tpu.transport import loopback_pair
from p2p_llm_tunnel_tpu.transport.chaos import ChaosChannel, ChaosSpec
from p2p_llm_tunnel_tpu.utils.metrics import global_metrics


def test_tunnel_reconnects_after_channel_kill(monkeypatch):
    pytest.importorskip("websockets")  # optional dep: skip where absent
    from p2p_llm_tunnel_tpu.signaling import SignalServer
    from p2p_llm_tunnel_tpu.transport import connect

    # shrink backoff so the test is fast (formula still 2*2^(n-1), capped)
    monkeypatch.setattr(cli, "INITIAL_BACKOFF", 0.1)
    monkeypatch.setattr(cli, "MAX_BACKOFF", 0.5)

    async def main():
        server = SignalServer(port=0)
        sig_port = await server.start()
        url = f"ws://127.0.0.1:{sig_port}"
        room = "reconnect-test"

        live = {}  # current serve-side channel, so the test can kill it
        proxy_port = {}

        async def upstream(req, body):
            async def chunks():
                yield b"pong"

            return 200, {"content-type": "text/plain"}, chunks()

        async def serve_once():
            ch, sig = await connect(url, room, "udp")
            live["serve"] = ch
            try:
                await run_serve(ch, backend=upstream)
            finally:
                ch.close()
                await sig.close()

        async def proxy_once():
            ch, sig = await connect(url, room, "udp")
            try:
                ready = asyncio.get_running_loop().create_future()
                task = asyncio.ensure_future(run_proxy(ch, "127.0.0.1", 0, ready=ready))
                proxy_port["port"] = await ready
                proxy_port["event"] = True
                await task
            finally:
                ch.close()
                await sig.close()

        serve_task = asyncio.ensure_future(
            cli.run_with_retry("serve", serve_once)
        )
        proxy_task = asyncio.ensure_future(
            cli.run_with_retry("proxy", proxy_once)
        )

        async def wait_ok(timeout=20.0):
            deadline = asyncio.get_running_loop().time() + timeout
            while True:
                try:
                    r = await http_request(
                        "GET", f"http://127.0.0.1:{proxy_port['port']}/x",
                        timeout=2.0,
                    )
                    if r.status == 200 and await r.read_all() == b"pong":
                        return
                except Exception:
                    pass
                if asyncio.get_running_loop().time() > deadline:
                    raise AssertionError("tunnel never became usable")
                await asyncio.sleep(0.3)

        try:
            # phase 1: up
            while "port" not in proxy_port:
                await asyncio.sleep(0.1)
            await wait_ok()

            # phase 2: kill the serve-side channel (transport failure)
            live["serve"].close()

            # phase 3: both supervisors reconnect; tunnel usable again.
            # (the proxy may rebind a new port on reconnect)
            await asyncio.sleep(1.0)
            await wait_ok()
        finally:
            serve_task.cancel()
            proxy_task.cancel()
            for t in (serve_task, proxy_task):
                with pytest.raises((asyncio.CancelledError, Exception)):
                    await t
            await server.stop()

    asyncio.run(asyncio.wait_for(main(), 60))


# ---------------------------------------------------------------------------
# ISSUE 8: mid-herd peer kill on a 3-peer fabric, seeded + deterministic
# ---------------------------------------------------------------------------

#: Chaos kill index for peer0's proxy-side channel.  Sends to peer0 are
#: HELLO(0), R1's REQ_HEADERS(1), R1's REQ_END(2) — so the NEXT dispatch
#: to peer0 (the first herd request the least-loaded picker routes there)
#: dies exactly at its own REQ_HEADERS frame, every run.
_KILL_AFTER = 3


def _fabric_kill_run(seed: int) -> dict:
    """One seeded herd run; returns the outcome record two runs must agree
    on.  Topology: 3 serve peers; peer0 carries a mid-stream SSE request
    and is killed by the chaos schedule while 5 gated requests are being
    dispatched across the fabric."""

    async def main():
        random.seed(seed)  # pins the re-dispatch backoff jitter
        state = ProxyState(fabric=True)
        hold = asyncio.Event()  # parks R1's SSE stream mid-flight
        gate = asyncio.Event()  # holds herd requests pre-headers

        def make_backend(name):
            async def backend(req, body):
                if req.path == "/sse":
                    async def sse():
                        yield b"data: start\n\n"
                        await hold.wait()
                        yield b"data: never\n\n"

                    return 200, {"content-type": "text/event-stream"}, sse()

                await gate.wait()

                async def chunks():
                    yield b"ok-" + name.encode()

                return 200, {"content-type": "text/plain"}, chunks()

            return backend

        ready: asyncio.Future = asyncio.get_running_loop().create_future()
        listener = asyncio.create_task(
            run_proxy_fabric(state, "127.0.0.1", 0, ready=ready))
        serve_tasks = []
        redisp0 = global_metrics.counter("proxy_redispatch_total")
        try:
            port = await asyncio.wait_for(ready, 5)
            base = f"http://127.0.0.1:{port}"

            # peer0 joins first, under the seeded kill schedule.  Resume
            # is disabled (stream_grace_s=0): this test pins the THIRD
            # tier of the failover contract — the typed peer_lost
            # terminal — and in a single-process fabric every serve peer
            # shares the detached-stream registry, so the mid-stream
            # victim would otherwise resume onto a survivor and park on
            # the never-set `hold` gate forever (tier 2 has its own
            # seeded suite: tests/test_resume.py).
            serve0, proxy0 = loopback_pair()
            serve_tasks.append(asyncio.create_task(
                run_serve(serve0, backend=make_backend("peer0"),
                          stream_grace_s=0)))
            chaos0 = ChaosChannel(
                proxy0, ChaosSpec.parse(f"kill={_KILL_AFTER},seed={seed}"))
            await state.admit(chaos0, peer_id="peer0")

            # R1: an SSE stream pinned to peer0 (the only peer) that has
            # already delivered bytes when the kill lands.
            r1 = await http_request("GET", f"{base}/sse", timeout=10)
            assert r1.status == 200
            r1_chunks = r1.iter_chunks()
            first = await r1_chunks.__anext__()
            assert b"start" in first

            # Survivors join.
            for i in (1, 2):
                s_ch, p_ch = loopback_pair()
                serve_tasks.append(asyncio.create_task(
                    run_serve(s_ch, backend=make_backend(f"peer{i}"),
                              stream_grace_s=0)))
                await state.admit(p_ch, peer_id=f"peer{i}")

            # The herd: 5 gated requests dispatched one at a time.  The
            # least-loaded picker MUST route at least one to peer0 (it
            # holds 1 stream, survivors fill to 2 each) — that dispatch
            # trips the kill schedule; the request must survive anyway.
            herd = []
            for i in range(5):
                herd.append(asyncio.create_task(http_request(
                    "GET", f"{base}/slow", timeout=15)))
                want = i + 1 + (1 if "peer0" in state.peers else 0)
                deadline = asyncio.get_running_loop().time() + 10
                while state.total_pending() != want:
                    # peer0's death mid-wait drops R1 from the pending set
                    # — recompute what "fully dispatched" means.
                    want = i + 1 + (1 if "peer0" in state.peers else 0)
                    assert asyncio.get_running_loop().time() < deadline
                    await asyncio.sleep(0.005)

            # The kill fired: peer0 is gone from the dispatchable set.
            assert "peer0" not in state.peers

            # (b) the mid-stream request ends with the TYPED peer_lost
            # finish, not a silent truncation.
            rest = b""
            async for c in r1_chunks:
                rest += c
            event = json.loads(rest.split(b"data: ", 1)[1])
            r1_class = event["error"]["code"]

            # (a) every not-yet-streaming request survives via re-dispatch.
            gate.set()
            herd_out = []
            for t in herd:
                resp = await t
                herd_out.append((resp.status, (await resp.read_all()).decode()))

            return {
                "herd": herd_out,
                "r1": r1_class,
                "redispatches": int(global_metrics.counter(
                    "proxy_redispatch_total") - redisp0),
                "failover_recorded": global_metrics.percentile(
                    "proxy_failover_ms", 50) > 0.0,
            }
        finally:
            listener.cancel()
            for t in serve_tasks:
                t.cancel()
            await asyncio.gather(
                listener, *serve_tasks, return_exceptions=True)

    return asyncio.run(asyncio.wait_for(main(), 30))


def test_fabric_midstream_peer_kill_seeded_deterministic():
    """Kill one of three serve peers mid-herd under the seeded chaos
    schedule: (a) zero failures among not-yet-streaming requests, (b) a
    typed peer_lost finish on the mid-stream one, (c) identical outcomes
    across two seeded runs, with the failover recovery time measured."""
    seed = int(os.environ.get("CHAOS_TEST_SEED", "5"))
    one = _fabric_kill_run(seed)
    two = _fabric_kill_run(seed)
    assert one == two, f"seeded runs diverged:\n{one}\n{two}"

    # (a) zero failed requests among the not-yet-streaming herd.
    assert [s for s, _ in one["herd"]] == [200] * 5
    # Every body came from a SURVIVOR or completed before the kill —
    # nothing was silently dropped.
    assert all(body.startswith("ok-peer") for _, body in one["herd"])
    # (b) typed error, from the ERROR_CODES registry.
    assert one["r1"] == "peer_lost"
    # The dispatch the kill interrupted (plus any aborted pre-headers
    # dispatches on peer0) was transparently re-dispatched...
    assert one["redispatches"] >= 1
    # ...and the recovery time landed in the catalogued histogram.
    assert one["failover_recorded"]
