"""Failure/recovery: the supervisor reconnects peers after a transport kill.

Exercises SURVEY.md §3.5 — transport dies → endpoints raise → run_with_retry
re-runs connect() → fresh channel, fresh handshake — which even the
reference only covers manually (its scripts never fault-inject).
"""

import asyncio
import json

import pytest

pytest.importorskip("websockets")  # optional dep: skip (not fail) where absent

from p2p_llm_tunnel_tpu import cli
from p2p_llm_tunnel_tpu.endpoints.http11 import http_request
from p2p_llm_tunnel_tpu.endpoints.proxy import run_proxy
from p2p_llm_tunnel_tpu.endpoints.serve import run_serve
from p2p_llm_tunnel_tpu.signaling import SignalServer
from p2p_llm_tunnel_tpu.transport import connect


def test_tunnel_reconnects_after_channel_kill(monkeypatch):
    # shrink backoff so the test is fast (formula still 2*2^(n-1), capped)
    monkeypatch.setattr(cli, "INITIAL_BACKOFF", 0.1)
    monkeypatch.setattr(cli, "MAX_BACKOFF", 0.5)

    async def main():
        server = SignalServer(port=0)
        sig_port = await server.start()
        url = f"ws://127.0.0.1:{sig_port}"
        room = "reconnect-test"

        live = {}  # current serve-side channel, so the test can kill it
        proxy_port = {}

        async def upstream(req, body):
            async def chunks():
                yield b"pong"

            return 200, {"content-type": "text/plain"}, chunks()

        async def serve_once():
            ch, sig = await connect(url, room, "udp")
            live["serve"] = ch
            try:
                await run_serve(ch, backend=upstream)
            finally:
                ch.close()
                await sig.close()

        async def proxy_once():
            ch, sig = await connect(url, room, "udp")
            try:
                ready = asyncio.get_running_loop().create_future()
                task = asyncio.ensure_future(run_proxy(ch, "127.0.0.1", 0, ready=ready))
                proxy_port["port"] = await ready
                proxy_port["event"] = True
                await task
            finally:
                ch.close()
                await sig.close()

        serve_task = asyncio.ensure_future(
            cli.run_with_retry("serve", serve_once)
        )
        proxy_task = asyncio.ensure_future(
            cli.run_with_retry("proxy", proxy_once)
        )

        async def wait_ok(timeout=20.0):
            deadline = asyncio.get_running_loop().time() + timeout
            while True:
                try:
                    r = await http_request(
                        "GET", f"http://127.0.0.1:{proxy_port['port']}/x",
                        timeout=2.0,
                    )
                    if r.status == 200 and await r.read_all() == b"pong":
                        return
                except Exception:
                    pass
                if asyncio.get_running_loop().time() > deadline:
                    raise AssertionError("tunnel never became usable")
                await asyncio.sleep(0.3)

        try:
            # phase 1: up
            while "port" not in proxy_port:
                await asyncio.sleep(0.1)
            await wait_ok()

            # phase 2: kill the serve-side channel (transport failure)
            live["serve"].close()

            # phase 3: both supervisors reconnect; tunnel usable again.
            # (the proxy may rebind a new port on reconnect)
            await asyncio.sleep(1.0)
            await wait_ok()
        finally:
            serve_task.cancel()
            proxy_task.cancel()
            for t in (serve_task, proxy_task):
                with pytest.raises((asyncio.CancelledError, Exception)):
                    await t
            await server.stop()

    asyncio.run(asyncio.wait_for(main(), 60))
