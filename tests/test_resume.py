"""Mid-stream continuity (ISSUE 13): resumable token streams across
tunnel resets.

The contract under test, end to end:

- a seeded ``kill=`` chaos schedule murders the channel carrying an SSE
  stream MID-FLIGHT; the serve side parks the stream (engine generation
  uncancelled, replay journal filling), the proxy holds the client
  response open, and a re-dialed peer splices the journal at exactly the
  delivered-byte offset — the client-observed body is BYTE-IDENTICAL to
  an unfaulted run, with exactly one ``serve_stream_resumes_total``
  increment, identical across two seeded runs;
- with resume disabled (grace 0) or the grace window expired, the
  behavior is exactly today's typed ``peer_lost`` terminal — the failure
  mode narrows, it never changes shape;
- the replay journal is a hard per-stream memory bound, held under a
  ``bw=`` slow-reader fault composed with the kill;
- a draining serve either flushes detached journals inside the
  ``--drain-timeout`` budget or NAMES the abandoned streams in the
  drain postmortem attribution;
- registrations leak nothing: post-run the detached gauge and replay
  bytes are zero (loadgen's /healthz leak check reads the same section).
"""

import asyncio
import json
import os
import random

import pytest

from p2p_llm_tunnel_tpu.endpoints.http11 import http_request
from p2p_llm_tunnel_tpu.endpoints.proxy import ProxyState, run_proxy_fabric
from p2p_llm_tunnel_tpu.endpoints.resume import (
    ReplayJournal,
    global_streams,
)
from p2p_llm_tunnel_tpu.endpoints.serve import run_serve
from p2p_llm_tunnel_tpu.protocol.frames import (
    Agree,
    Hello,
    MessageType,
    ResponseHeaders,
    ResumeFrame,
    TunnelMessage,
)
from p2p_llm_tunnel_tpu.transport import loopback_pair
from p2p_llm_tunnel_tpu.transport.chaos import ChaosChannel, ChaosSpec
from p2p_llm_tunnel_tpu.utils.flight import global_blackbox
from p2p_llm_tunnel_tpu.utils.metrics import global_metrics

SEED = int(os.environ.get("CHAOS_TEST_SEED", "5"))

#: Serve-side chaos kill index: sends are AGREE(0), RES_HEADERS(1),
#: RES_BODY "start"(2) — the gate guarantees those land, so kill at 6
#: always fires MID-BODY, a few coalesced frames into the tail.
KILL_AFTER = 6


# ---------------------------------------------------------------------------
# units: replay journal + wire codec
# ---------------------------------------------------------------------------

def test_replay_journal_offsets_trim_and_meter():
    seen = []
    j = ReplayJournal(meter=seen.append)
    j.append(b"abcdef")
    j.append(b"ghij")
    assert (j.base, j.end, j.size) == (0, 10, 10)
    assert j.slice_from(3, 4) == b"defg"
    j.trim_to(4)
    assert (j.base, j.end, j.size) == (4, 10, 6)
    assert j.covers(4) and j.covers(10) and not j.covers(3)
    assert j.slice_from(4) == b"efghij"
    # trim below base is a no-op; truncate drops the unsent tail
    j.trim_to(2)
    assert j.base == 4
    j.truncate_to(7)
    assert (j.base, j.end) == (4, 7) and j.slice_from(4) == b"efg"
    assert sum(seen) == j.size  # meter deltas reconcile with residency


def test_resume_frame_codec_roundtrip_and_bounds():
    rf = ResumeFrame(7, "rs-abc", 4096, epoch=2)
    back = ResumeFrame.from_json(TunnelMessage.res_resume(rf).payload)
    assert (back.token, back.offset, back.epoch) == ("rs-abc", 4096, 2)
    from p2p_llm_tunnel_tpu.protocol.frames import ProtocolError

    with pytest.raises(ProtocolError):
        ResumeFrame.from_json(json.dumps(
            {"stream_id": 1, "token": "x" * 100, "offset": 0}
        ).encode())
    with pytest.raises(ProtocolError):
        ResumeFrame.from_json(json.dumps(
            {"stream_id": 1, "token": "t", "offset": -1}
        ).encode())


def test_response_headers_resume_extension_is_wire_invisible_when_off():
    """A non-resumable response's RES_HEADERS payload must be EXACTLY the
    legacy key set (reference peers see an unchanged wire); the extension
    keys appear only when a token was minted, and unknown-key-tolerant
    parsing round-trips both."""
    legacy = ResponseHeaders(3, 200, {"a": "b"})
    assert set(json.loads(legacy.to_json())) == {
        "stream_id", "status", "headers"
    }
    ext = ResponseHeaders(3, 200, {"a": "b"}, resume="rs-x", grace=5.0)
    obj = json.loads(ext.to_json())
    assert obj["resume"] == "rs-x" and obj["grace"] == 5.0
    back = ResponseHeaders.from_json(ext.to_json())
    assert (back.resume, back.grace) == ("rs-x", 5.0)
    assert ResponseHeaders.from_json(legacy.to_json()).resume == ""


# ---------------------------------------------------------------------------
# harness: 1-peer fabric, serve-side seeded kill, optional re-admit
# ---------------------------------------------------------------------------

def _gauges_clean() -> dict:
    return {
        "detached": int(global_metrics.gauge("serve_streams_detached")),
        "replay_bytes": int(
            global_metrics.gauge("serve_replay_buffer_bytes")
        ),
        "live": global_streams.live_count(),
    }


async def _cancel_all(*tasks: "asyncio.Task") -> None:
    """Teardown that survives the Python 3.10 wait_for cancellation
    swallow: a task cancelled at the exact moment its awaited future
    completes (run_serve's handshake recv under a racing re-admit) keeps
    running — so re-cancel until everything is done."""
    for _ in range(5):
        for t in tasks:
            t.cancel()
        done, pending = await asyncio.wait(set(tasks), timeout=2.0)
        if not pending:
            return
    raise AssertionError(f"tasks survived repeated cancellation: {pending}")


async def _drain_settled(timeout: float = 5.0) -> None:
    """Wait for the registry to empty (grace expiries included)."""
    deadline = asyncio.get_running_loop().time() + timeout
    while global_streams.live_count() > 0:
        assert asyncio.get_running_loop().time() < deadline, \
            "detached-stream registry never drained"
        await asyncio.sleep(0.02)


def _kill_run(seed: int, kill: int, readmit: bool, grace_s: float = 3.0,
              journal_bytes: int = 512 * 1024, n_events: int = 30,
              chaos_extra: str = "", sample_journal: bool = False) -> dict:
    """One seeded mid-stream-kill run; returns the outcome record two
    seeded runs must agree on."""

    async def main():
        random.seed(seed)
        state = ProxyState(fabric=True)
        gate = asyncio.Event()

        async def backend(req, body):
            async def sse():
                yield b"data: start\n\n"
                await gate.wait()
                for i in range(n_events):
                    yield f"data: tok-{i}\n\n".encode()
                    await asyncio.sleep(0)

            return 200, {"content-type": "text/event-stream"}, sse()

        def serve_once(channel):
            return run_serve(channel, backend=backend,
                             stream_grace_s=grace_s,
                             stream_journal_bytes=journal_bytes)

        ready = asyncio.get_running_loop().create_future()
        listener = asyncio.create_task(
            run_proxy_fabric(state, "127.0.0.1", 0, ready=ready))
        serve_tasks = []
        helpers = []
        r0 = global_metrics.counter("serve_stream_resumes_total")
        try:
            port = await asyncio.wait_for(ready, 5)
            serve0, proxy0 = loopback_pair()
            ch = serve0
            if kill:
                spec = f"kill={kill},seed={seed}"
                if chaos_extra:
                    spec += "," + chaos_extra
                ch = ChaosChannel(serve0, ChaosSpec.parse(spec))
            serve_tasks.append(asyncio.create_task(serve_once(ch)))
            await state.admit(proxy0, peer_id="peer0")

            r = await http_request(
                "GET", f"http://127.0.0.1:{port}/sse", timeout=20)
            assert r.status == 200
            it = r.iter_chunks()
            first = await it.__anext__()
            assert b"start" in first
            gate.set()

            async def readmitter():
                while "peer0" in state.peers:
                    await asyncio.sleep(0.01)
                s2, p2 = loopback_pair()
                serve_tasks.append(asyncio.create_task(serve_once(s2)))
                await state.admit(p2, peer_id="peer0")

            if kill and readmit:
                helpers.append(asyncio.create_task(readmitter()))

            max_journal = 0

            async def journal_sampler():
                nonlocal max_journal
                while True:
                    max_journal = max(max_journal, int(
                        global_metrics.gauge("serve_replay_buffer_bytes")
                    ))
                    await asyncio.sleep(0.002)

            if sample_journal:
                helpers.append(asyncio.create_task(journal_sampler()))

            body = first
            async for c in it:
                body += c
            await _drain_settled()
            return {
                "body": body,
                "resumes": int(global_metrics.counter(
                    "serve_stream_resumes_total") - r0),
                "resume_ms_recorded": global_metrics.percentile(
                    "proxy_stream_resume_ms", 50) > 0.0,
                "clean": _gauges_clean(),
                "max_journal": max_journal,
            }
        finally:
            await _cancel_all(listener, *serve_tasks, *helpers)

    return asyncio.run(asyncio.wait_for(main(), 30))


# ---------------------------------------------------------------------------
# chaos proof: byte-identical resume, exactly once, seeded-deterministic
# ---------------------------------------------------------------------------

def test_midstream_kill_resume_byte_identical_seeded():
    """Seeded kill= mid-stream with recovery inside the grace window →
    the client receives a byte-identical complete stream (vs an unfaulted
    run) with exactly ONE resume, identical across two seeded runs, and
    the detached registry + replay buffers released afterward."""
    baseline = _kill_run(SEED, kill=0, readmit=False)
    one = _kill_run(SEED, kill=KILL_AFTER, readmit=True)
    two = _kill_run(SEED, kill=KILL_AFTER, readmit=True)
    assert one == two, f"seeded runs diverged:\n{one}\n{two}"
    assert one["body"] == baseline["body"]
    assert one["resumes"] == 1
    assert one["resume_ms_recorded"]
    assert one["clean"] == {"detached": 0, "replay_bytes": 0, "live": 0}
    assert baseline["resumes"] == 0


def test_midstream_kill_grace_expiry_is_typed_peer_lost():
    """The grace-expiry twin: the peer never comes back, so after the
    window the stream ends with EXACTLY today's typed peer_lost terminal
    event — the failure mode is narrowed, never swapped — and the parked
    generation is cancelled (registry drains to zero)."""
    out = _kill_run(SEED, kill=KILL_AFTER, readmit=False, grace_s=0.4)
    tail = out["body"].split(b"data: ")[-1]
    event = json.loads(tail)
    assert event["error"]["code"] == "peer_lost"
    assert out["resumes"] == 0
    assert out["clean"] == {"detached": 0, "replay_bytes": 0, "live": 0}


def test_midstream_kill_resume_disabled_is_legacy_path():
    """--stream-grace-s 0 disables resume wholesale: no token on the
    wire, and a mid-stream kill is immediately today's typed peer_lost."""
    out = _kill_run(SEED, kill=KILL_AFTER, readmit=True, grace_s=0.0)
    event = json.loads(out["body"].split(b"data: ")[-1])
    assert event["error"]["code"] == "peer_lost"
    assert out["resumes"] == 0


def test_journal_bound_holds_under_slow_reader_with_kill():
    """kill= composed with the bw= slow-reader fault and a TINY journal
    cap: the stream still resumes byte-identically, and the replay buffer
    gauge never exceeds cap + one coalesced chunk — the journal is a hard
    memory bound under a lagging client, not an unbounded buffer."""
    from p2p_llm_tunnel_tpu.protocol.frames import MAX_BODY_CHUNK

    cap = 4096
    baseline = _kill_run(SEED, kill=0, readmit=False, n_events=120)
    out = _kill_run(SEED, kill=KILL_AFTER, readmit=True, grace_s=5.0,
                    journal_bytes=cap, n_events=120,
                    chaos_extra="bw=2e5", sample_journal=True)
    assert out["body"] == baseline["body"]
    assert out["resumes"] >= 1
    assert 0 < out["max_journal"] <= cap + MAX_BODY_CHUNK
    assert out["clean"] == {"detached": 0, "replay_bytes": 0, "live": 0}


# ---------------------------------------------------------------------------
# resume refusal: unknown token answers typed, never hangs
# ---------------------------------------------------------------------------

def test_resume_unknown_token_refused_typed():
    async def main():
        async def backend(req, body):
            async def chunks():
                yield b"ok"

            return 200, {"content-type": "text/plain"}, chunks()

        serve_ch, client_ch = loopback_pair()
        serve_task = asyncio.create_task(
            run_serve(serve_ch, backend=backend))
        try:
            await client_ch.send(TunnelMessage.hello(Hello()).encode())
            agree = TunnelMessage.decode(await client_ch.recv())
            assert agree.msg_type == MessageType.AGREE
            Agree.from_json(agree.payload)
            await client_ch.send(TunnelMessage.res_resume(
                ResumeFrame(9, "rs-never-existed", 0, 0)
            ).encode())
            msg = TunnelMessage.decode(
                await asyncio.wait_for(client_ch.recv(), 5))
            assert msg.msg_type == MessageType.ERROR
            assert msg.error_code() == "peer_lost"
            assert msg.stream_id == 9
        finally:
            serve_task.cancel()
            await asyncio.gather(serve_task, return_exceptions=True)

    asyncio.run(asyncio.wait_for(main(), 15))


# ---------------------------------------------------------------------------
# drain interaction: flush-or-name (ISSUE 13 satellite)
# ---------------------------------------------------------------------------

def _drain_run(grace_s: float, drain_timeout: float) -> dict:
    """Detach a stream by killing its session, tear the proxy down (so
    nothing can resume it), then drain a FRESH serve session while the
    stream is still parked; returns what the drain did."""

    async def main():
        state = ProxyState(fabric=True)
        gate = asyncio.Event()

        async def backend(req, body):
            async def sse():
                yield b"data: start\n\n"
                await gate.wait()
                yield b"data: never\n\n"

            return 200, {"content-type": "text/event-stream"}, sse()

        ready = asyncio.get_running_loop().create_future()
        listener = asyncio.create_task(
            run_proxy_fabric(state, "127.0.0.1", 0, ready=ready))
        serve1_ch, proxy1_ch = loopback_pair()
        serve1 = asyncio.create_task(run_serve(
            serve1_ch, backend=backend, stream_grace_s=grace_s))
        captures0 = global_blackbox.section()["captured"]
        try:
            port = await asyncio.wait_for(ready, 5)
            await state.admit(proxy1_ch, peer_id="peer0")
            r = await http_request(
                "GET", f"http://127.0.0.1:{port}/sse", timeout=10)
            it = r.iter_chunks()
            assert b"start" in await it.__anext__()
            # Kill session 1: the stream parks in the global registry.
            serve1_ch.close()
            deadline = asyncio.get_running_loop().time() + 5
            while global_streams.count_detached() == 0:
                assert asyncio.get_running_loop().time() < deadline
                await asyncio.sleep(0.01)
            tokens = global_streams.detached_tokens()
            # Tear the proxy down so NOTHING can resume the parked
            # stream — the drain under test must face it alone.
            listener.cancel()
            await asyncio.gather(listener, return_exceptions=True)
            r.close()

            # Session 2 (hand-shaken directly) drains with the stream
            # still parked.
            serve2_ch, proxy2_ch = loopback_pair()
            drain = asyncio.Event()
            drain.set()
            serve2 = asyncio.ensure_future(run_serve(
                serve2_ch, backend=backend, drain=drain,
                drain_timeout=drain_timeout, stream_grace_s=grace_s,
            ))
            await proxy2_ch.send(TunnelMessage.hello(Hello()).encode())
            agree = TunnelMessage.decode(
                await asyncio.wait_for(proxy2_ch.recv(), 5))
            assert agree.msg_type == MessageType.AGREE
            await asyncio.wait_for(serve2, 10)
            section = global_blackbox.section()
            new_capture = section["captured"] - captures0
            await _drain_settled(timeout=max(2.0, 2 * grace_s))
            return {
                "tokens": tokens,
                "captures": new_capture,
                "attribution": (section["postmortem"] or {}).get(
                    "attribution", ""),
                "clean": _gauges_clean(),
            }
        finally:
            await _cancel_all(listener, serve1)

    return asyncio.run(asyncio.wait_for(main(), 30))


def test_drain_timeout_names_abandoned_detached_streams():
    """A drain that cannot outlast a parked stream's grace window must
    NAME the abandoned stream in the postmortem attribution — today a
    detached stream would silently extend or silently vanish."""
    out = _drain_run(grace_s=5.0, drain_timeout=0.3)
    assert out["captures"] == 1
    assert "resumable stream(s) abandoned" in out["attribution"]
    assert out["tokens"] and out["tokens"][0] in out["attribution"]


def test_drain_flushes_detached_journals_inside_budget():
    """When the grace window expires INSIDE the drain budget, the drain
    completes cleanly — registry flushed, no postmortem capture."""
    out = _drain_run(grace_s=0.3, drain_timeout=5.0)
    assert out["captures"] == 0
    assert out["clean"] == {"detached": 0, "replay_bytes": 0, "live": 0}


def test_drain_ignores_other_sessions_healthy_streams():
    """A multi-session process: session A's drain must not block on (or
    name) a stream healthily attached to session B's channel — the drain
    wait is scoped to THIS channel plus unowned detached streams."""

    async def main():
        state = ProxyState(fabric=True)
        gate = asyncio.Event()

        async def backend(req, body):
            async def sse():
                yield b"data: start\n\n"
                await gate.wait()
                yield b"data: end\n\n"

            return 200, {"content-type": "text/event-stream"}, sse()

        ready = asyncio.get_running_loop().create_future()
        listener = asyncio.create_task(
            run_proxy_fabric(state, "127.0.0.1", 0, ready=ready))
        serveB_ch, proxyB_ch = loopback_pair()
        serveB = asyncio.create_task(run_serve(
            serveB_ch, backend=backend, stream_grace_s=5.0))
        captures0 = global_blackbox.section()["captured"]
        try:
            port = await asyncio.wait_for(ready, 5)
            await state.admit(proxyB_ch, peer_id="peerB")
            r = await http_request(
                "GET", f"http://127.0.0.1:{port}/sse", timeout=10)
            it = r.iter_chunks()
            assert b"start" in await it.__anext__()
            assert global_streams.live_count() == 1  # B's healthy stream

            # Session A drains with drain_timeout=0 (wait FOREVER): were
            # the wait global, B's gated stream would hang it.
            serveA_ch, proxyA_ch = loopback_pair()
            drain = asyncio.Event()
            drain.set()
            serveA = asyncio.ensure_future(run_serve(
                serveA_ch, backend=backend, drain=drain,
                drain_timeout=0.0, stream_grace_s=5.0))
            await proxyA_ch.send(TunnelMessage.hello(Hello()).encode())
            agree = TunnelMessage.decode(
                await asyncio.wait_for(proxyA_ch.recv(), 5))
            assert agree.msg_type == MessageType.AGREE
            await asyncio.wait_for(serveA, 5)
            assert global_blackbox.section()["captured"] == captures0

            gate.set()
            rest = b""
            async for c in it:
                rest += c
            assert b"end" in rest
            await _drain_settled()
        finally:
            await _cancel_all(listener, serveB)

    asyncio.run(asyncio.wait_for(main(), 30))


def test_proxy_error_frame_reparks_resumed_attachment():
    """An abandoned resume must never orphan-wedge the relay: if the
    proxy cancels a (possibly late-accepted) resumed attachment with a
    typed ERROR on its stream id, the serve side re-parks the stream —
    back into the grace window — instead of pumping frames nobody
    demuxes until flow credit wedges it forever."""

    async def main():
        state = ProxyState(fabric=True)
        gate = asyncio.Event()

        async def backend(req, body):
            async def sse():
                yield b"data: start\n\n"
                await gate.wait()
                yield b"data: never\n\n"

            return 200, {"content-type": "text/event-stream"}, sse()

        ready = asyncio.get_running_loop().create_future()
        listener = asyncio.create_task(
            run_proxy_fabric(state, "127.0.0.1", 0, ready=ready))
        serve1_ch, proxy1_ch = loopback_pair()
        serve1 = asyncio.create_task(run_serve(
            serve1_ch, backend=backend, stream_grace_s=1.0))
        try:
            port = await asyncio.wait_for(ready, 5)
            await state.admit(proxy1_ch, peer_id="peer0")
            r = await http_request(
                "GET", f"http://127.0.0.1:{port}/sse", timeout=10)
            it = r.iter_chunks()
            assert b"start" in await it.__anext__()
            # Park the stream, then silence the proxy (no auto-resume).
            listener.cancel()
            await asyncio.gather(listener, return_exceptions=True)
            serve1_ch.close()
            deadline = asyncio.get_running_loop().time() + 5
            while global_streams.count_detached() == 0:
                assert asyncio.get_running_loop().time() < deadline
                await asyncio.sleep(0.01)
            token = global_streams.detached_tokens()[0]

            # Hand-rolled session 2: resume it, then cancel the resumed
            # attachment with a typed ERROR on its stream id.
            serve2_ch, proxy2_ch = loopback_pair()
            serve2 = asyncio.create_task(run_serve(
                serve2_ch, backend=backend, stream_grace_s=1.0))
            await proxy2_ch.send(TunnelMessage.hello(Hello()).encode())
            agree = TunnelMessage.decode(
                await asyncio.wait_for(proxy2_ch.recv(), 5))
            assert agree.msg_type == MessageType.AGREE
            await proxy2_ch.send(TunnelMessage.res_resume(
                ResumeFrame(77, token, 0, 0)).encode())
            msg = TunnelMessage.decode(
                await asyncio.wait_for(proxy2_ch.recv(), 5))
            assert msg.msg_type == MessageType.RES_RESUMED
            assert global_streams.count_detached() == 0  # attached again
            await proxy2_ch.send(TunnelMessage.typed_error(
                77, "peer_lost", "resume abandoned by proxy").encode())
            deadline = asyncio.get_running_loop().time() + 5
            while global_streams.count_detached() == 0:
                assert asyncio.get_running_loop().time() < deadline
                await asyncio.sleep(0.01)
            # Re-parked: the fresh grace window expires and releases it.
            await _drain_settled(timeout=5.0)
            r.close()
            await _cancel_all(serve2)
        finally:
            await _cancel_all(listener, serve1)

    asyncio.run(asyncio.wait_for(main(), 30))


# ---------------------------------------------------------------------------
# healthz surfaces (ISSUE 13 satellite)
# ---------------------------------------------------------------------------

def test_healthz_streams_section_and_proxy_resume_snapshot():
    async def main():
        state = ProxyState(fabric=True)

        async def backend(req, body):
            async def chunks():
                yield b"ok"

            return 200, {"content-type": "text/plain"}, chunks()

        ready = asyncio.get_running_loop().create_future()
        listener = asyncio.create_task(
            run_proxy_fabric(state, "127.0.0.1", 0, ready=ready))
        serve_ch, proxy_ch = loopback_pair()
        serve_task = asyncio.create_task(
            run_serve(serve_ch, backend=backend))
        try:
            port = await asyncio.wait_for(ready, 5)
            await state.admit(proxy_ch, peer_id="peer0")
            r = await http_request(
                "GET", f"http://127.0.0.1:{port}/healthz", timeout=10)
            hz = json.loads(await r.read_all())
            assert "streams" in hz
            assert set(hz["streams"]) == {
                "detached", "resumable_live", "replay_buffer_bytes",
                "resumes_total",
            }
            r2 = await http_request(
                "GET", f"http://127.0.0.1:{port}/healthz?local=1",
                timeout=10)
            snap = json.loads(await r2.read_all())
            assert "stream_resume_p50_ms" in snap
        finally:
            listener.cancel()
            serve_task.cancel()
            await asyncio.gather(
                listener, serve_task, return_exceptions=True)

    asyncio.run(asyncio.wait_for(main(), 15))
