"""Ring attention vs dense causal attention on the 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from p2p_llm_tunnel_tpu.ops.ring_attention import (
    make_ring_attention,
    ring_attention_reference,
)
from p2p_llm_tunnel_tpu.parallel import make_mesh

# Compile-heavy (JAX jit of engine/model programs): excluded from
# `make test-fast` (VERDICT r4 item 8).
pytestmark = pytest.mark.slow


def _qkv(key, b, t, h, kh, d, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, t, h, d), dtype)
    k = jax.random.normal(kk, (b, t, kh, d), dtype)
    v = jax.random.normal(kv, (b, t, kh, d), dtype)
    return q, k, v


@pytest.mark.parametrize("sp", [2, 4, 8])
def test_ring_matches_dense(sp, cpu_devices):
    mesh = make_mesh(sp=sp, dp=1, tp=1)
    q, k, v = _qkv(jax.random.PRNGKey(0), b=2, t=64, h=4, kh=2, d=16)
    ring = jax.jit(make_ring_attention(mesh))
    got = ring(q, k, v)
    want = ring_attention_reference(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)


def test_ring_with_softcap(cpu_devices):
    mesh = make_mesh(sp=4, dp=1, tp=1)
    q, k, v = _qkv(jax.random.PRNGKey(1), b=1, t=32, h=4, kh=4, d=8)
    ring = jax.jit(make_ring_attention(mesh, softcap=30.0))
    got = ring(q, k, v)
    want = ring_attention_reference(q, k, v, softcap=30.0)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)


def test_ring_causality(cpu_devices):
    """Changing future tokens must not change earlier outputs."""
    mesh = make_mesh(sp=4, dp=1, tp=1)
    q, k, v = _qkv(jax.random.PRNGKey(2), b=1, t=32, h=2, kh=2, d=8)
    ring = jax.jit(make_ring_attention(mesh))
    base = np.asarray(ring(q, k, v))
    # perturb the last quarter of k/v (the final device's block)
    k2 = k.at[:, 24:].set(jax.random.normal(jax.random.PRNGKey(9), k[:, 24:].shape))
    v2 = v.at[:, 24:].set(jax.random.normal(jax.random.PRNGKey(10), v[:, 24:].shape))
    pert = np.asarray(ring(q, k2, v2))
    np.testing.assert_allclose(pert[:, :24], base[:, :24], rtol=1e-5, atol=1e-5)
    assert not np.allclose(pert[:, 24:], base[:, 24:])


def test_ring_bf16_stable(cpu_devices):
    mesh = make_mesh(sp=2, dp=1, tp=1)
    q, k, v = _qkv(jax.random.PRNGKey(3), b=1, t=16, h=2, kh=1, d=8, dtype=jnp.bfloat16)
    got = jax.jit(make_ring_attention(mesh))(q, k, v)
    want = ring_attention_reference(q, k, v)
    assert got.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32), rtol=5e-2, atol=5e-2
    )
