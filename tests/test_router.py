"""DP replica router: least-loaded dispatch across engine replicas."""

import asyncio
import json

from p2p_llm_tunnel_tpu.engine.engine import EngineConfig, InferenceEngine
from p2p_llm_tunnel_tpu.engine.router import ReplicaRouter
from p2p_llm_tunnel_tpu.protocol.frames import RequestHeaders

import pytest

# Compile-heavy (JAX jit of engine/model programs): excluded from
# `make test-fast` (VERDICT r4 item 8).
pytestmark = pytest.mark.slow


def _engines(n):
    return [
        InferenceEngine(
            engine_cfg=EngineConfig(model="tiny", num_slots=2, max_seq=64,
                                    dtype="float32", decode_steps=2, seed=i)
        )
        for i in range(n)
    ]


def test_pick_round_robins_when_idle(cpu_devices):
    router = ReplicaRouter(_engines(3))
    picks = {router.pick() for _ in range(9)}
    assert picks == {0, 1, 2}


def test_pick_prefers_least_loaded(cpu_devices):
    engines = _engines(2)
    router = ReplicaRouter(engines)
    # Load replica 0's queue artificially.
    from p2p_llm_tunnel_tpu.engine.scheduler import GenRequest

    engines[0].scheduler.submit(GenRequest(1, [1, 2], 4))
    engines[0].scheduler.submit(GenRequest(2, [1, 2], 4))
    assert all(router.pick() == 1 for _ in range(5))


def test_requests_spread_across_replicas(cpu_devices):
    async def main():
        engines = _engines(2)
        router = ReplicaRouter(engines, "tiny")
        await router.start()
        try:
            async def one(i):
                req = RequestHeaders(i, "POST", "/v1/completions", {})
                body = json.dumps({
                    "prompt": f"spread {i}", "max_tokens": 6,
                    "ignore_eos": True,
                }).encode()
                status, headers, chunks = await router.handle(req, body)
                assert status == 200
                async for _ in chunks:
                    pass

            await asyncio.gather(*(one(i) for i in range(1, 7)))
        finally:
            await router.stop()
        # Both replicas saw work (6 requests, 2 slots each, least-loaded).
        return [e.scheduler.num_slots for e in engines]

    asyncio.run(asyncio.wait_for(main(), 180))
