"""Sampler correctness: greedy exactness, top-k/top-p support restriction."""

import jax
import jax.numpy as jnp
import numpy as np

from p2p_llm_tunnel_tpu.engine.sampling import SamplingParams, make_params, sample

import pytest

# Compile-heavy (JAX jit of engine/model programs): excluded from
# `make test-fast` (VERDICT r4 item 8).
pytestmark = pytest.mark.slow


def logits_fixture(b=4, v=32):
    return jax.random.normal(jax.random.PRNGKey(0), (b, v)) * 3.0


def test_greedy_exact():
    logits = logits_fixture()
    out = sample(logits, make_params(4, temperature=0.0), jax.random.PRNGKey(1))
    np.testing.assert_array_equal(np.asarray(out), np.argmax(np.asarray(logits), -1))


def test_temperature_samples_vary():
    logits = jnp.zeros((2, 16))  # uniform → sampling must not be constant
    outs = {
        tuple(np.asarray(sample(logits, make_params(2, temperature=1.0),
                                jax.random.PRNGKey(i))))
        for i in range(16)
    }
    assert len(outs) > 1


def test_top_k_restricts_support():
    logits = logits_fixture(b=1, v=64)
    top2 = set(np.argsort(np.asarray(logits[0]))[-2:].tolist())
    for i in range(32):
        out = sample(
            logits, make_params(1, temperature=1.0, top_k=2), jax.random.PRNGKey(i)
        )
        assert int(out[0]) in top2


def test_top_p_restricts_support():
    # One dominant token (p≈0.97) → top_p=0.5 must always pick it.
    logits = jnp.full((1, 16), -2.0).at[0, 7].set(4.0)
    for i in range(32):
        out = sample(
            logits, make_params(1, temperature=1.0, top_p=0.5), jax.random.PRNGKey(i)
        )
        assert int(out[0]) == 7


def test_mixed_batch_per_slot_params():
    """Greedy and sampling rows coexist in one batch (no recompiles)."""
    logits = logits_fixture(b=3, v=16)
    params = SamplingParams(
        temperature=jnp.array([0.0, 1.0, 0.0]),
        top_k=jnp.array([0, 4, 0]),
        top_p=jnp.array([1.0, 1.0, 1.0]),
        freq_pen=jnp.zeros((3,)),
        pres_pen=jnp.zeros((3,)),
        logprobs=jnp.zeros((3,), jnp.int32),
    )
    out = np.asarray(sample(logits, params, jax.random.PRNGKey(3)))
    ref = np.argmax(np.asarray(logits), -1)
    assert out[0] == ref[0] and out[2] == ref[2]


def test_jit_stable():
    f = jax.jit(sample)
    logits = logits_fixture()
    a = f(logits, make_params(4), jax.random.PRNGKey(0))
    b = sample(logits, make_params(4), jax.random.PRNGKey(0))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_all_greedy_batch_skips_stochastic_path():
    """All-greedy batches take the lax.cond fast path; result is pure argmax
    regardless of top_k/top_p settings (those only gate stochastic rows)."""
    logits = logits_fixture()
    params = SamplingParams(
        temperature=jnp.zeros((4,)),
        top_k=jnp.full((4,), 2, jnp.int32),
        top_p=jnp.full((4,), 0.5),
        freq_pen=jnp.zeros((4,)),
        pres_pen=jnp.zeros((4,)),
        logprobs=jnp.zeros((4,), jnp.int32),
    )
    out = sample(logits, params, jax.random.PRNGKey(3))
    np.testing.assert_array_equal(
        np.asarray(out), np.argmax(np.asarray(logits), -1)
    )


def test_mixed_greedy_and_stochastic_rows_still_exact():
    """One stochastic row forces the full path; greedy rows stay argmax."""
    logits = logits_fixture()
    params = SamplingParams(
        temperature=jnp.array([0.0, 1.0, 0.0, 0.0]),
        top_k=jnp.zeros((4,), jnp.int32),
        top_p=jnp.ones((4,)),
        freq_pen=jnp.zeros((4,)),
        pres_pen=jnp.zeros((4,)),
        logprobs=jnp.zeros((4,), jnp.int32),
    )
    out = np.asarray(sample(logits, params, jax.random.PRNGKey(4)))
    ref = np.argmax(np.asarray(logits), -1)
    for i in (0, 2, 3):
        assert out[i] == ref[i]
