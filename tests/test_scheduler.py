"""Continuous-batching scheduler unit tests (pure logic, fake streams)."""

import pytest

from p2p_llm_tunnel_tpu.engine.scheduler import GenRequest, Scheduler


def req(rid, prompt_len=4, max_new=8, stop=()):
    return GenRequest(rid, list(range(1, prompt_len + 1)), max_new, stop_ids=stop)


def test_fifo_admission():
    s = Scheduler(num_slots=2, max_seq=64)
    for i in range(4):
        s.submit(req(i))
    admitted = s.admit()
    assert [r.request.request_id for r in admitted] == [0, 1]
    assert s.queue_depth == 2
    assert s.occupancy == 1.0
    assert s.admit() == []  # no free slots


def test_eviction_on_stop_token():
    s = Scheduler(1, 64)
    s.submit(req(7, stop=(99,)))
    (run,) = s.admit()
    s.record_token(run.slot, 5)
    assert s.slots[run.slot] is not None
    s.record_token(run.slot, 99)  # stop token
    assert s.slots[run.slot] is None


def test_eviction_on_length():
    s = Scheduler(1, 64)
    s.submit(req(1, max_new=3))
    (run,) = s.admit()
    for tok in (10, 11):
        s.record_token(run.slot, tok)
        assert s.slots[run.slot] is not None
    s.record_token(run.slot, 12)
    assert s.slots[run.slot] is None
    assert run.generated == [10, 11, 12]


def test_eviction_on_cache_capacity():
    s = Scheduler(1, max_seq=6)
    s.submit(req(2, prompt_len=4, max_new=100))
    (run,) = s.admit()
    s.record_token(run.slot, 1)  # cache_len 5
    assert s.slots[run.slot] is not None
    s.record_token(run.slot, 2)  # cache_len 6 == max_seq → evict
    assert s.slots[run.slot] is None


def test_freed_slot_readmits_from_queue():
    s = Scheduler(1, 64)
    s.submit(req(1, max_new=1))
    s.submit(req(2))
    (run,) = s.admit()
    assert run.request.request_id == 1
    s.record_token(run.slot, 5)  # finishes request 1
    (run2,) = s.admit()
    assert run2.request.request_id == 2


def test_cancel_waiting_and_active():
    s = Scheduler(2, 64)
    s.submit(req(1))
    s.submit(req(2))
    s.submit(req(3))
    s.admit()
    assert s.cancel(3) is True  # still waiting
    assert s.cancel(1) is True  # active in a slot
    assert s.cancel(99) is False
    assert s.queue_depth == 0
    assert s.occupancy == 0.5


def test_prompt_too_long_rejected():
    s = Scheduler(1, max_seq=8)
    with pytest.raises(ValueError):
        s.submit(req(1, prompt_len=8))


def test_invalid_requests_rejected():
    with pytest.raises(ValueError):
        GenRequest(1, [], 5)
    with pytest.raises(ValueError):
        GenRequest(1, [1], 0)


def test_mixed_cancel_and_deadline_expiry_same_step():
    """A cancel and deadline expiries landing in the same engine step must
    resolve deterministically: the cancel applies first (consumer is gone),
    then expire() evicts waiting requests in FIFO order, then running
    slots by slot index — never dict/iteration-order dependent."""
    def dreq(rid, deadline=None, prompt_len=4):
        return GenRequest(
            rid, list(range(1, prompt_len + 1)), 8, deadline=deadline
        )

    s = Scheduler(num_slots=2, max_seq=64)
    s.submit(dreq(1, deadline=1.0))
    s.submit(dreq(2))  # no deadline, will be cancelled
    s.admit()  # 1 → slot 0, 2 → slot 1
    s.submit(dreq(3, deadline=1.0))  # waiting, expired
    s.submit(dreq(4))  # waiting, immune

    # Same step: consumer of 2 cancels, then the step's expiry sweep runs.
    assert s.cancel(2) is True
    expired = s.expire(now=2.0)
    assert [(slot, r.request_id) for slot, r in expired] == [
        (None, 3),  # waiting first, FIFO order
        (0, 1),     # then running slots by index
    ]
    # Cancelled and expired slots are both reclaimed; 4 survives untouched.
    assert s.slots == [None, None]
    assert [r.request_id for r in s.waiting] == [4]
    # The freed slots readmit the survivor (FIFO → lowest free slot).
    (run,) = s.admit()
    assert run.request.request_id == 4 and run.slot == 0
    # A second sweep is a no-op: expiry must be idempotent.
    assert s.expire(now=3.0) == []


def test_expired_request_never_admits():
    """An expired waiting request must be evicted by the sweep, not handed
    a slot afterwards."""
    s = Scheduler(num_slots=1, max_seq=64)
    s.submit(GenRequest(1, [1, 2], 8, deadline=1.0))
    assert [r.request_id for _, r in s.expire(now=5.0)] == [1]
    assert s.admit() == []
    assert s.idle


def test_many_requests_through_few_slots():
    """Simulated drain: 20 requests through 4 slots, random-ish lengths."""
    s = Scheduler(4, 64)
    for i in range(20):
        s.submit(req(i, prompt_len=2 + i % 5, max_new=1 + i % 7))
    finished = []
    steps = 0
    while not s.idle:
        s.admit()
        for run in list(s.active()):
            s.record_token(run.slot, 1000 + steps)
            if s.slots[run.slot] is None:
                finished.append(run.request.request_id)
        steps += 1
        assert steps < 1000, "scheduler did not drain"
    assert sorted(finished) == list(range(20))
