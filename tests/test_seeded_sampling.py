"""Per-request seeded sampling: reproducible AND batch-composition
independent.

Row randomness is a pure function of (seed, token position) — the
property that makes `seed` requests reproducible across runs and makes a
request's samples identical whether it ran alone or packed in a batch
(vLLM's per-request seeds; OpenAI's `seed` parameter).
"""

import asyncio

import pytest

from p2p_llm_tunnel_tpu.engine.engine import EngineConfig, InferenceEngine

# Compile-heavy (JAX jit of engine/model programs): excluded from
# `make test-fast` (VERDICT r4 item 8).
pytestmark = pytest.mark.slow

ECFG = EngineConfig(model="tiny", num_slots=4, max_seq=64, dtype="float32",
                    seed=0)


async def _collect(engine, prompt, **kw):
    out = []
    async for ev in engine.generate(prompt, max_new_tokens=6, stop_ids=(),
                                    **kw):
        out.append(ev.token_id)
    return out


def test_same_seed_reproduces_different_seed_varies():
    async def run():
        engine = InferenceEngine(engine_cfg=ECFG)
        await engine.start()
        try:
            a = await _collect(engine, [1, 2, 3], temperature=0.9, seed=7)
            b = await _collect(engine, [1, 2, 3], temperature=0.9, seed=7)
            c = await _collect(engine, [1, 2, 3], temperature=0.9, seed=8)
            assert a == b, "same seed must reproduce exactly"
            assert a != c, "different seeds should diverge (tiny vocab: " \
                           "astronomically unlikely to collide on 6 tokens)"
        finally:
            await engine.stop()

    asyncio.run(run())


def test_seeded_sampling_independent_of_batch_composition():
    """A seeded request's tokens must not change when other requests share
    the batch — each row's key stream is its own."""
    async def run():
        engine = InferenceEngine(engine_cfg=ECFG)
        await engine.start()
        try:
            solo = await _collect(engine, [5, 6, 7], temperature=0.8,
                                  seed=42)
            packed = await asyncio.gather(
                _collect(engine, [5, 6, 7], temperature=0.8, seed=42),
                _collect(engine, [9, 9], temperature=1.2, seed=3),
                _collect(engine, [4, 4, 4, 4], temperature=0.5, seed=11),
            )
            assert packed[0] == solo, (
                "batch composition changed a seeded request's tokens"
            )
        finally:
            await engine.stop()

    asyncio.run(run())


def test_api_seed_param_reproduces():
    from tests.test_engine_tunnel import engine_stack
    from p2p_llm_tunnel_tpu.endpoints import http11
    import json

    async def run():
        async with engine_stack() as (base, _):
            async def once():
                resp = await http11.http_request(
                    "POST", f"{base}/v1/completions",
                    {"content-type": "application/json"},
                    json.dumps({"prompt": "abc", "max_tokens": 5,
                                "temperature": 0.9, "seed": 123,
                                "ignore_eos": True}).encode(),
                    timeout=60.0,
                )
                return json.loads(await resp.read_all())["choices"][0]["text"]

            t1, t2 = await once(), await once()
            assert t1 == t2

    asyncio.run(run())


def test_api_seed_with_n_still_yields_distinct_choices():
    """OpenAI semantics: seed pins the RUN, not one shared sample stream —
    n choices must still differ from each other (per-run seed offsets),
    while the whole response reproduces across calls."""
    from tests.test_engine_tunnel import engine_stack
    from p2p_llm_tunnel_tpu.endpoints import http11
    import json

    async def run():
        async with engine_stack() as (base, _):
            async def once():
                resp = await http11.http_request(
                    "POST", f"{base}/v1/completions",
                    {"content-type": "application/json"},
                    json.dumps({"prompt": "abc", "max_tokens": 6,
                                "temperature": 1.0, "seed": 5, "n": 3,
                                "ignore_eos": True}).encode(),
                    timeout=60.0,
                )
                obj = json.loads(await resp.read_all())
                return [c["text"] for c in obj["choices"]]

            a, b = await once(), await once()
            assert a == b, "seeded n-response must reproduce as a whole"
            assert len(set(a)) > 1, "n choices collapsed to one sample"

    asyncio.run(run())
