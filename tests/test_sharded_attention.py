"""Sharded prefill attention paths (VERDICT r2 item 6).

- the Pallas flash kernel shard_map'd over tp head-shards matches the dense
  oracle (interpret mode on the CPU mesh);
- ring attention composes with tp (heads AND sequence sharded);
- full prefill under a tp mesh with flash enabled matches the einsum path;
- the engine serves a prompt longer than one sp shard's sequence block.
"""

import asyncio

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from p2p_llm_tunnel_tpu.engine.engine import EngineConfig, InferenceEngine
from p2p_llm_tunnel_tpu.models.config import get_config
from p2p_llm_tunnel_tpu.models.transformer import init_params, prefill
from p2p_llm_tunnel_tpu.ops.attention import causal_attention
from p2p_llm_tunnel_tpu.ops.ring_attention import (
    make_ring_attention,
    ring_attention_reference,
)
from p2p_llm_tunnel_tpu.parallel import make_mesh

# Compile-heavy (JAX jit of engine/model programs): excluded from
# `make test-fast` (VERDICT r4 item 8).
pytestmark = pytest.mark.slow


def _qkv(key, b, t, h, kh, d):
    kq, kk, kv = jax.random.split(key, 3)
    return (
        jax.random.normal(kq, (b, t, h, d), jnp.float32),
        jax.random.normal(kk, (b, t, kh, d), jnp.float32),
        jax.random.normal(kv, (b, t, kh, d), jnp.float32),
    )


def test_flash_tp_shardmap_matches_dense(cpu_devices):
    """shard_map'd flash kernel over tp=2 head shards == dense oracle."""
    from p2p_llm_tunnel_tpu.models.transformer import _prefill_attention_fn

    mesh = make_mesh(tp=2, dp=1)
    cfg = get_config(
        "tiny", n_heads=4, n_kv_heads=2, head_dim=128,
        flash=True, flash_interpret=True,
    )
    t = 256
    q, k, v = _qkv(jax.random.PRNGKey(0), b=2, t=t, h=4, kh=2, d=128)
    valid = jnp.ones((2, t), bool)
    attn = _prefill_attention_fn(cfg, mesh, t)
    got = jax.jit(lambda *a: attn(*a, None))(q, k, v, valid)
    want = causal_attention(q, k, v, valid)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)


def test_ring_composes_with_tp(cpu_devices):
    """Ring attention with heads sharded on tp AND sequence on sp."""
    mesh = make_mesh(tp=2, dp=1, sp=4)
    q, k, v = _qkv(jax.random.PRNGKey(1), b=2, t=64, h=4, kh=2, d=16)
    ring = make_ring_attention(mesh, "sp", head_axis="tp")
    got = jax.jit(ring)(q, k, v)
    want = ring_attention_reference(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)


def test_prefill_sp_mesh_matches_dense(cpu_devices):
    """Full prefill forward under an sp=2/tp=2 mesh == unsharded prefill."""
    cfg = get_config("tiny", n_heads=4, n_kv_heads=2, vocab_size=512)
    assert cfg.sliding_window is None
    params = init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    b, t = 2, 64
    tokens = jax.random.randint(jax.random.PRNGKey(1), (b, t), 0, cfg.vocab_size)
    valid = jnp.ones((b, t), bool)

    logits_ref, ks_ref, vs_ref = prefill(cfg, params, tokens, valid)

    mesh = make_mesh(tp=2, dp=1, sp=2)
    logits_s, ks_s, vs_s = jax.jit(
        lambda p, tk, vl: prefill(cfg, p, tk, vl, mesh=mesh)
    )(params, tokens, valid)
    np.testing.assert_allclose(
        np.asarray(logits_s), np.asarray(logits_ref), rtol=2e-4, atol=2e-4
    )
    np.testing.assert_allclose(
        np.asarray(ks_s), np.asarray(ks_ref), rtol=2e-4, atol=2e-4
    )


def test_prefill_sp_rejects_sliding_window(cpu_devices):
    cfg = get_config("tiny-gemma")
    assert cfg.sliding_window is not None
    params = init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    mesh = make_mesh(tp=1, dp=1, sp=2)
    tokens = jnp.zeros((1, 32), jnp.int32)
    with pytest.raises(NotImplementedError):
        prefill(cfg, params, tokens, jnp.ones((1, 32), bool), mesh=mesh)


def _collect(engine, prompt, n):
    async def main():
        await engine.start()
        toks = []
        async for ev in engine.generate(prompt, max_new_tokens=n, stop_ids=()):
            toks.append(ev.token_id)
        await engine.stop()
        return toks

    return asyncio.run(asyncio.wait_for(main(), 120))


def test_engine_sp_serves_long_prompt(cpu_devices):
    """Engine on an sp=2 mesh serves a prompt spanning both sequence shards
    (prompt 40 tokens -> bucket 64 -> 32 per shard) and matches the
    single-chip engine stream."""
    cfg = get_config("tiny", n_heads=4, n_kv_heads=2, vocab_size=512)
    params = init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    prompt = list(range(3, 43))  # 40 tokens > one sp shard's 32-token block

    single = InferenceEngine(
        model_cfg=cfg, params=params,
        engine_cfg=EngineConfig(model="tiny", num_slots=2, max_seq=128,
                                dtype="float32", decode_steps=4),
    )
    want = _collect(single, prompt, 8)

    sp_engine = InferenceEngine(
        model_cfg=cfg, params=params,
        engine_cfg=EngineConfig(model="tiny", num_slots=2, max_seq=128,
                                dtype="float32", decode_steps=4, sp=2),
    )
    assert dict(sp_engine.mesh.shape)["sp"] == 2
    got = _collect(sp_engine, prompt, 8)
    assert got == want
