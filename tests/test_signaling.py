"""Signaling server + client tests: in-process rendezvous.

Asserts the reference room semantics (signal-server/src/index.ts:112-220):
join/joined/peer-joined, verbatim relay with `from`, room-full error,
peer-left on disconnect, bye handling.
"""

import asyncio

import pytest

pytest.importorskip("websockets")  # optional dep: skip (not fail) where absent

from p2p_llm_tunnel_tpu.signaling import SignalServer, SignalingClient
from p2p_llm_tunnel_tpu.signaling.client import (
    Answer,
    Candidate,
    Joined,
    Offer,
    PeerJoined,
    PeerLeft,
    SignalError,
)


def run(coro):
    return asyncio.run(asyncio.wait_for(coro, 30))


async def _start_server():
    server = SignalServer(port=0)
    port = await server.start()
    return server, f"ws://127.0.0.1:{port}"


def test_join_and_peer_joined():
    async def main():
        server, url = await _start_server()
        a = await SignalingClient.connect(url, "room1")
        joined_a = await a.recv(5)
        assert isinstance(joined_a, Joined) and joined_a.peers == []

        b = await SignalingClient.connect(url, "room1")
        joined_b = await b.recv(5)
        assert isinstance(joined_b, Joined)
        assert joined_b.peers == [joined_a.peer_id]

        notify = await a.recv(5)
        assert isinstance(notify, PeerJoined)
        assert notify.peer_id == joined_b.peer_id

        await a.close()
        await b.close()
        await server.stop()

    run(main())


def test_offer_answer_candidate_relay():
    async def main():
        server, url = await _start_server()
        a = await SignalingClient.connect(url, "r")
        await a.recv(5)  # joined
        b = await SignalingClient.connect(url, "r")
        await b.recv(5)  # joined
        await a.recv(5)  # peer-joined

        sdp = {"type": "offer", "sdp": "v=0 fake"}
        await a.send_offer(sdp)
        got = await b.recv(5)
        assert isinstance(got, Offer) and got.sdp == sdp and got.sender

        await b.send_answer({"type": "answer", "sdp": "v=0 reply"})
        got = await a.recv(5)
        assert isinstance(got, Answer) and got.sdp["sdp"] == "v=0 reply"

        cand = {"candidate": "udp 1.2.3.4 5", "sdpMid": "0"}
        await b.send_candidate(cand)
        got = await a.recv(5)
        assert isinstance(got, Candidate) and got.candidate == cand

        await a.close()
        await b.close()
        await server.stop()

    run(main())


def test_room_full():
    async def main():
        server, url = await _start_server()
        a = await SignalingClient.connect(url, "full")
        await a.recv(5)
        b = await SignalingClient.connect(url, "full")
        await b.recv(5)
        c = await SignalingClient.connect(url, "full")
        got = await c.recv(5)
        assert isinstance(got, SignalError) and "full" in got.message

        for cl in (a, b, c):
            await cl.close()
        await server.stop()

    run(main())


def test_peer_left_on_disconnect():
    async def main():
        server, url = await _start_server()
        a = await SignalingClient.connect(url, "x")
        ja = await a.recv(5)
        b = await SignalingClient.connect(url, "x")
        await b.recv(5)
        await a.recv(5)  # peer-joined

        await b.close()  # sends bye
        got = await a.recv(5)
        assert isinstance(got, PeerLeft)

        # Room now has one occupant; a third join succeeds again.
        c = await SignalingClient.connect(url, "x")
        jc = await c.recv(5)
        assert isinstance(jc, Joined) and jc.peers == [ja.peer_id]

        await a.close()
        await c.close()
        await server.stop()

    run(main())


def test_relay_without_peer_errors():
    async def main():
        server, url = await _start_server()
        a = await SignalingClient.connect(url, "solo")
        await a.recv(5)
        await a.send_offer({"sdp": "nobody home"})
        got = await a.recv(5)
        assert isinstance(got, SignalError)
        await a.close()
        await server.stop()

    run(main())
