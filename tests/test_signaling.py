"""Signaling server + client tests: in-process rendezvous.

Asserts the reference room semantics (signal-server/src/index.ts:112-220):
join/joined/peer-joined, verbatim relay with `from`, room-full error,
peer-left on disconnect, bye handling.
"""

import asyncio

import pytest

pytest.importorskip("websockets")  # optional dep: skip (not fail) where absent

from p2p_llm_tunnel_tpu.signaling import SignalServer, SignalingClient
from p2p_llm_tunnel_tpu.signaling.client import (
    Answer,
    Candidate,
    Joined,
    Offer,
    PeerJoined,
    PeerLeft,
    SignalError,
)


def run(coro):
    return asyncio.run(asyncio.wait_for(coro, 30))


async def _start_server():
    server = SignalServer(port=0)
    port = await server.start()
    return server, f"ws://127.0.0.1:{port}"


def test_join_and_peer_joined():
    async def main():
        server, url = await _start_server()
        a = await SignalingClient.connect(url, "room1")
        joined_a = await a.recv(5)
        assert isinstance(joined_a, Joined) and joined_a.peers == []

        b = await SignalingClient.connect(url, "room1")
        joined_b = await b.recv(5)
        assert isinstance(joined_b, Joined)
        assert joined_b.peers == [joined_a.peer_id]

        notify = await a.recv(5)
        assert isinstance(notify, PeerJoined)
        assert notify.peer_id == joined_b.peer_id

        await a.close()
        await b.close()
        await server.stop()

    run(main())


def test_offer_answer_candidate_relay():
    async def main():
        server, url = await _start_server()
        a = await SignalingClient.connect(url, "r")
        await a.recv(5)  # joined
        b = await SignalingClient.connect(url, "r")
        await b.recv(5)  # joined
        await a.recv(5)  # peer-joined

        sdp = {"type": "offer", "sdp": "v=0 fake"}
        await a.send_offer(sdp)
        got = await b.recv(5)
        assert isinstance(got, Offer) and got.sdp == sdp and got.sender

        await b.send_answer({"type": "answer", "sdp": "v=0 reply"})
        got = await a.recv(5)
        assert isinstance(got, Answer) and got.sdp["sdp"] == "v=0 reply"

        cand = {"candidate": "udp 1.2.3.4 5", "sdpMid": "0"}
        await b.send_candidate(cand)
        got = await a.recv(5)
        assert isinstance(got, Candidate) and got.candidate == cand

        await a.close()
        await b.close()
        await server.stop()

    run(main())


def test_room_full():
    async def main():
        server, url = await _start_server()
        a = await SignalingClient.connect(url, "full")
        await a.recv(5)
        b = await SignalingClient.connect(url, "full")
        await b.recv(5)
        c = await SignalingClient.connect(url, "full")
        got = await c.recv(5)
        assert isinstance(got, SignalError) and "full" in got.message

        for cl in (a, b, c):
            await cl.close()
        await server.stop()

    run(main())


def test_peer_left_on_disconnect():
    async def main():
        server, url = await _start_server()
        a = await SignalingClient.connect(url, "x")
        ja = await a.recv(5)
        b = await SignalingClient.connect(url, "x")
        await b.recv(5)
        await a.recv(5)  # peer-joined

        await b.close()  # sends bye
        got = await a.recv(5)
        assert isinstance(got, PeerLeft)

        # Room now has one occupant; a third join succeeds again.
        c = await SignalingClient.connect(url, "x")
        jc = await c.recv(5)
        assert isinstance(jc, Joined) and jc.peers == [ja.peer_id]

        await a.close()
        await c.close()
        await server.stop()

    run(main())


def test_relay_without_peer_errors():
    async def main():
        server, url = await _start_server()
        a = await SignalingClient.connect(url, "solo")
        await a.recv(5)
        await a.send_offer({"sdp": "nobody home"})
        got = await a.recv(5)
        assert isinstance(got, SignalError)
        await a.close()
        await server.stop()

    run(main())


# ---------------------------------------------------------------------------
# role-tagged fabric rooms (ISSUE 8): per-role caps, targeted relay, fan-out
# ---------------------------------------------------------------------------


def test_role_tagged_room_roles_and_caps():
    async def main():
        server, url = await _start_server()
        server.max_serve_peers = 2

        p = await SignalingClient.connect(url, "fab", role="proxy")
        jp = await p.recv(5)
        assert isinstance(jp, Joined) and jp.roles == {}

        s1 = await SignalingClient.connect(url, "fab", role="serve")
        js1 = await s1.recv(5)
        assert js1.roles == {jp.peer_id: "proxy"}
        ev = await p.recv(5)
        assert isinstance(ev, PeerJoined) and ev.role == "serve"

        s2 = await SignalingClient.connect(url, "fab", role="serve")
        js2 = await s2.recv(5)
        assert js2.roles == {jp.peer_id: "proxy", js1.peer_id: "serve"}
        # peer-joined fans out to EVERY occupant, not just "the other one".
        assert isinstance(await p.recv(5), PeerJoined)
        assert isinstance(await s1.recv(5), PeerJoined)

        # Per-role caps: a second proxy and a third serve are both refused.
        p2 = await SignalingClient.connect(url, "fab", role="proxy")
        got = await p2.recv(5)
        assert isinstance(got, SignalError) and "proxy" in got.message
        s3 = await SignalingClient.connect(url, "fab", role="serve")
        got = await s3.recv(5)
        assert isinstance(got, SignalError) and "full" in got.message

        # An unknown role is refused loudly, not silently untagged.
        x = await SignalingClient.connect(url, "fab", role="router")
        got = await x.recv(5)
        assert isinstance(got, SignalError) and "unknown role" in got.message

        for cl in (p, s1, s2, p2, s3, x):
            await cl.close()
        await server.stop()

    run(main())


def test_targeted_relay_in_n_peer_room():
    async def main():
        server, url = await _start_server()
        p = await SignalingClient.connect(url, "fab2", role="proxy")
        jp = await p.recv(5)
        s1 = await SignalingClient.connect(url, "fab2", role="serve")
        js1 = await s1.recv(5)
        s2 = await SignalingClient.connect(url, "fab2", role="serve")
        js2 = await s2.recv(5)
        await p.recv(5)  # peer-joined s1
        await p.recv(5)  # peer-joined s2
        await s1.recv(5)  # peer-joined s2

        # Untargeted relay is ambiguous once the room holds 3 peers.
        await p.send_offer({"sdp": "x"})
        got = await p.recv(5)
        assert isinstance(got, SignalError) and "ambiguous" in got.message

        # Targeted offers reach exactly the addressed peer, from= stamped.
        await p.send_offer({"sdp": "to-s2"}, to=js2.peer_id)
        got = await s2.recv(5)
        assert isinstance(got, Offer) and got.sdp == {"sdp": "to-s2"}
        assert got.sender == jp.peer_id

        # The answerer's reply_to pin targets the offerer without a `to`.
        s2.reply_to = got.sender
        await s2.send_answer({"sdp": "reply"})
        got = await p.recv(5)
        assert isinstance(got, Answer) and got.sender == js2.peer_id

        # Targeting a peer outside the room errors back to the sender.
        await p.send_offer({"sdp": "x"}, to="nope")
        got = await p.recv(5)
        assert isinstance(got, SignalError) and "no such peer" in got.message

        # s1 must have seen none of the s2-addressed traffic.
        await s1.send_candidate({"candidate": "c"}, to=jp.peer_id)
        got = await p.recv(5)
        assert isinstance(got, Candidate) and got.sender == js1.peer_id

        for cl in (p, s1, s2):
            await cl.close()
        await server.stop()

    run(main())


def test_peer_left_fans_out_with_role():
    async def main():
        server, url = await _start_server()
        p = await SignalingClient.connect(url, "fab3", role="proxy")
        await p.recv(5)
        s1 = await SignalingClient.connect(url, "fab3", role="serve")
        js1 = await s1.recv(5)
        s2 = await SignalingClient.connect(url, "fab3", role="serve")
        await s2.recv(5)
        await p.recv(5)
        await p.recv(5)
        await s1.recv(5)

        await s1.close()  # bye
        for cl in (p, s2):
            got = await cl.recv(5)
            assert isinstance(got, PeerLeft)
            assert got.peer_id == js1.peer_id and got.role == "serve"

        await p.close()
        await s2.close()
        await server.stop()

    run(main())


def test_server_stop_is_concurrent_safe_and_idempotent():
    """Regression for the tunnelcheck TC13 finding on SignalServer.stop():
    the old shape checked ``self._server``, awaited ``wait_closed()``, and
    only then cleared the handle — a concurrent stop() (entrypoint
    teardown racing a test's finally) could act on a handle the first
    caller was mid-way through tearing down.  stop() now claims the
    handle BEFORE the suspension, so every interleaving finds either the
    live server or None."""
    async def main():
        server, _url = await _start_server()
        await asyncio.gather(server.stop(), server.stop(), server.stop())
        assert server._server is None
        await server.stop()  # already stopped: a clean no-op

    run(main())
