"""SLO burn-rate engine (ISSUE 9, utils/slo.py): fake-clock unit tests.

The engine's charter is deterministic, injectable-clock evaluation: these
tests drive a fake clock through window expiry and pin the ok → burning →
breached → ok lifecycle, the count-ratio (never wall-rate) arithmetic, the
latency-threshold mapping, the labeled-gauge publication, and the
disabled-by-default no-op contract that keeps bare library use from ever
flipping a test /healthz status.
"""

from __future__ import annotations

from p2p_llm_tunnel_tpu.utils.metrics import Metrics
from p2p_llm_tunnel_tpu.utils.slo import (
    BURN_THRESHOLD,
    Objective,
    SloEngine,
    default_objectives,
    global_slo,
)


class FakeClock:
    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def engine(clock, **kw):
    kw.setdefault("min_events", 0)
    kw.setdefault("enabled", True)
    return SloEngine(
        [Objective("avail", 0.999),
         Objective("ttft", 0.99, threshold_ms=100.0)],
        clock=clock, **kw,
    )


def test_no_events_is_ok_with_zero_burn():
    e = engine(FakeClock())
    v = e.evaluate()
    assert v["avail"]["state"] == "ok"
    assert v["avail"]["burn_fast"] == 0.0
    assert v["avail"]["burn_slow"] == 0.0
    assert v["ttft"]["threshold_ms"] == 100.0


def test_burn_is_count_ratio_over_budget():
    clk = FakeClock()
    e = engine(clk)
    # 1 bad out of 100: err 0.01, budget 0.001 -> burn 10 in both windows.
    for _ in range(99):
        e.record("avail", True)
    e.record("avail", False)
    v = e.evaluate()["avail"]
    assert v["burn_fast"] == 10.0 and v["burn_slow"] == 10.0
    assert v["events_fast"] == 100 and v["events_slow"] == 100
    # 10 < 14.4: consuming budget but below the alert threshold.
    assert v["state"] == "ok"


def test_lifecycle_ok_burning_breached_and_decay():
    clk = FakeClock()
    e = engine(clk)
    # A healthy hour of history: 1000 good events, aged past the fast
    # window but inside the slow one.
    for _ in range(1000):
        e.record("avail", True)
    clk.advance(3000.0)
    # A fresh error burst: 5 bad / 5 good in the fast window.
    for _ in range(5):
        e.record("avail", False)
        e.record("avail", True)
    v = e.evaluate()["avail"]
    # fast: 5/10 err -> burn 500 >= 14.4; slow: 5/1010 ≈ 0.005 err ->
    # burn ≈ 5 < 14.4 — the multiwindow split that means BURNING, the
    # healthy history says it is not yet a sustained breach.
    assert v["state"] == "burning"
    assert v["burn_fast"] >= BURN_THRESHOLD
    assert v["burn_slow"] < BURN_THRESHOLD

    # The good history ages out of the slow window while the failure
    # CONTINUES -> both windows burn -> breached.
    clk.advance(1000.0)
    for _ in range(5):
        e.record("avail", False)
        e.record("avail", True)
    v = e.evaluate()["avail"]
    assert v["state"] == "breached"
    assert v["burn_fast"] >= BURN_THRESHOLD
    assert v["burn_slow"] >= BURN_THRESHOLD

    # Errors STOP: the fast window drains first, so the verdict decays
    # (a recovered peer must not stay de-routed for the slow window's
    # full hour), then everything ages out to zero burn.
    clk.advance(1000.0)
    v = e.evaluate()["avail"]
    assert v["state"] == "ok" and v["burn_slow"] > 0.0
    clk.advance(4000.0)
    v = e.evaluate()["avail"]
    assert v["state"] == "ok" and v["burn_slow"] == 0.0


def test_min_events_guard_suppresses_thin_evidence():
    clk = FakeClock()
    e = engine(clk, min_events=10)
    # 1 bad / 3 events would burn at 333x — but 3 < 10 events is not
    # evidence, and one unlucky request must not page.
    e.record("avail", False)
    e.record("avail", True)
    e.record("avail", True)
    assert e.evaluate()["avail"]["state"] == "ok"
    for _ in range(7):
        e.record("avail", False)
    assert e.evaluate()["avail"]["state"] == "breached"


def test_latency_objective_maps_threshold_to_good_bad():
    clk = FakeClock()
    e = engine(clk)
    for ms in (10.0, 50.0, 100.0):  # at-threshold counts good
        e.record_latency("ttft", ms)
    e.record_latency("ttft", 101.0)
    v = e.evaluate()["ttft"]
    assert v["events_slow"] == 4
    # 1/4 err over budget 0.01 -> burn 25 >= 14.4 in both windows.
    assert v["state"] == "breached"
    # Unknown objective and non-latency objective: ignored, never a crash.
    e.record_latency("nope", 1.0)
    e.record_latency("avail", 1.0)
    assert e.evaluate()["avail"]["events_slow"] == 0


def test_determinism_same_events_same_verdicts():
    def run():
        clk = FakeClock()
        e = engine(clk)
        for i in range(200):
            e.record("avail", i % 7 != 0)
            e.record_latency("ttft", float(i % 150))
            if i % 50 == 49:
                clk.advance(120.0)
        return e.evaluate()

    assert run() == run()


def test_reset_drops_events_keeps_config():
    clk = FakeClock()
    e = engine(clk)
    for _ in range(20):
        e.record("avail", False)
    assert e.evaluate()["avail"]["state"] == "breached"
    e.reset()
    v = e.evaluate()["avail"]
    assert v["state"] == "ok" and v["events_slow"] == 0
    assert "avail" in e.objectives  # objectives survive reset


def test_disabled_engine_is_inert_and_publishes_nothing():
    clk = FakeClock()
    e = engine(clk, enabled=False)
    e.record("avail", False)
    e.record_latency("ttft", 1e9)
    assert e.evaluate()["avail"]["events_slow"] == 0
    reg = Metrics()
    assert e.publish(reg) == {}
    assert reg.labeled_gauge("slo_state") == {}
    sec = e.section()
    assert sec["enabled"] is False and sec["alerting"] is False


def test_publish_writes_labeled_catalog_series():
    clk = FakeClock()
    e = engine(clk)
    for _ in range(20):
        e.record("avail", False)
    reg = Metrics()
    verdicts = e.publish(reg)
    assert verdicts["avail"]["state"] == "breached"
    assert reg.labeled_gauge("slo_state")["avail"] == 2.0
    assert reg.labeled_gauge("slo_burn_fast")["avail"] > 0
    text = reg.prometheus_text()
    assert 'slo_state{objective="avail"} 2' in text
    assert 'slo_burn_slow{objective="ttft"} 0' in text


def test_section_alerting_flag_follows_worst_objective():
    clk = FakeClock()
    e = engine(clk)
    sec = e.section()
    assert sec["enabled"] is True and sec["alerting"] is False
    for _ in range(20):
        e.record("avail", False)
    sec = e.section()
    assert sec["alerting"] is True
    assert sec["objectives"]["avail"]["state"] == "breached"
    assert sec["objectives"]["ttft"]["state"] == "ok"


def test_configure_replaces_objectives_and_drops_history():
    clk = FakeClock()
    e = engine(clk)
    for _ in range(20):
        e.record("avail", False)
    e.configure(objectives=[Objective("avail", 0.5)])
    v = e.evaluate()["avail"]
    assert v["events_slow"] == 0 and v["target"] == 0.5


def test_default_objectives_and_global_engine_posture():
    objs = {o.name: o for o in default_objectives(
        ttft_ms=750.0, ttft_target=0.95, availability_target=0.99)}
    assert objs["ttft"].threshold_ms == 750.0
    assert objs["ttft"].target == 0.95
    assert objs["availability"].target == 0.99
    # The process-global engine ships DISABLED (library use must never
    # flip a /healthz status); the serve CLI enables it.
    assert global_slo.enabled is False
    assert {"ttft", "availability"} <= set(global_slo.objectives)


def test_zero_budget_objective_burns_not_crashes():
    clk = FakeClock()
    e = SloEngine([Objective("strict", 1.0)], clock=clk,
                  min_events=0, enabled=True)
    e.record("strict", True)
    assert e.evaluate()["strict"]["state"] == "ok"
    e.record("strict", False)
    assert e.evaluate()["strict"]["state"] == "breached"


def test_burning_needs_fast_window_evidence_too():
    """min_events guards BOTH windows for the burning verdict: an hour of
    healthy history plus ONE transient 502 in a near-empty fast window
    must not de-route the peer for five minutes (review find)."""
    clk = FakeClock()
    e = engine(clk, min_events=10)
    for _ in range(1000):
        e.record("avail", True)
    clk.advance(3000.0)  # history ages out of the fast window only
    e.record("avail", False)  # one lonely fast-window event
    v = e.evaluate()["avail"]
    assert v["events_fast"] == 1
    assert v["burn_fast"] >= BURN_THRESHOLD  # the ratio alone would page
    assert v["state"] == "ok"  # ...but one event is not evidence
    # With real fast-window evidence the same ratio DOES burn.
    for _ in range(6):
        e.record("avail", False)
        e.record("avail", True)
    assert e.evaluate()["avail"]["state"] == "burning"
