"""Prompt-lookup speculative decoding: exact-greedy acceptance.

The contract is absolute: speculation is a pure latency optimization —
token output must be IDENTICAL to plain decode (greedy acceptance only
admits tokens greedy decoding would have produced), for greedy rows,
stochastic rows (which accept nothing and sample their own stream), stop
sequences, and token limits alike.
"""

import asyncio

import pytest

from p2p_llm_tunnel_tpu.engine.engine import EngineConfig, InferenceEngine
from p2p_llm_tunnel_tpu.utils.metrics import global_metrics

# The full acceptance suite is compile-heavy (JAX jit of engine/model
# programs) and stays slow-tier (VERDICT r4 item 8) — but the core
# greedy-equivalence contract runs in tier-1 (ISSUE 17 satellite):
# test_greedy_spec_equivalence_tier1 below is deliberately UNMARKED so a
# spec regression fails `make test`, not only the slow runs.
slow = pytest.mark.slow


def _cfg(**kw):
    base = dict(model="tiny", num_slots=4, max_seq=128, dtype="float32",
                seed=0)
    base.update(kw)
    return EngineConfig(**base)


async def _collect(engine, prompt, max_new=24, **kw):
    out = []
    async for ev in engine.generate(prompt, max_new_tokens=max_new,
                                    stop_ids=(), **kw):
        out.append(ev.token_id)
    return out


#: Highly repetitive prompt: the ngram proposer should fire constantly.
REP = list(b"the cat sat on the mat. the cat sat on the mat. the cat")


def test_greedy_spec_equivalence_tier1():
    """Tier-1 (ISSUE 17 satellite): greedy token streams are byte-identical
    spec-on vs spec-off at EVERY kv_quant mode — including int4, which was
    fenced off speculation before the fused verify burst landed.  The
    horizon is short (the verify path fires on every proposal whether or
    not anything is accepted), so this runs in `make test` and catches a
    spec regression without waiting for the slow tier."""
    async def run(spec, kv):
        engine = InferenceEngine(
            engine_cfg=_cfg(spec_ngram=3 if spec else 0, spec_k=4,
                            kv_quant=kv, max_seq=256))
        await engine.start()
        try:
            global_metrics.reset()
            out = await _collect(engine, REP, max_new=32)
            proposed = global_metrics.counter(
                "engine_spec_proposed_tokens_total")
            return out, proposed
        finally:
            await engine.stop()

    for kv in ("none", "int8", "int4"):
        plain, _ = asyncio.run(run(False, kv))
        spec, proposed = asyncio.run(run(True, kv))
        assert spec == plain, f"speculation changed greedy output (kv={kv})"
        assert proposed > 0, f"verify path never fired (kv={kv})"
    assert global_metrics.gauge("engine_spec_hist_entries") == 0


def test_spec_composes_with_hero_config_no_fences():
    """ISSUE 17 acceptance: spec_ngram under int4 weights + int4 KV +
    fused decode layer + mux leaves the config_fences registry EMPTY —
    the last composition fence is gone.  Construction-time check: fences
    are registered at engine init."""
    engine = InferenceEngine(engine_cfg=_cfg(
        spec_ngram=3, spec_k=4, spec_k_max=8, quant="int4",
        kv_quant="int4", fused_decode_layer=True, mux=True,
        prefix_cache=True, max_seq=256))
    assert engine.config_fences == [], engine.config_fences
    assert engine.ecfg.spec_ngram == 3
    # The warmup plan carries the fused spec-verify ladder for the combo.
    assert [s for k, s in engine.warmup_plan() if k == "spec"]


@slow
def test_greedy_equivalence_and_acceptance():
    # Acceptance needs the GREEDY STREAM (not just the prompt) to repeat
    # its own n-grams: the random tiny model's trajectory settles into a
    # cycle only after ~3 dozen tokens (the r2-r8 numerics work — int4,
    # fused decode, mux — shifted where the cycle starts, which is what
    # silently broke this test at the old 24-token horizon).  96 tokens
    # reaches the cycle with margin while equivalence still binds every
    # token.
    async def run(spec):
        engine = InferenceEngine(
            engine_cfg=_cfg(spec_ngram=3 if spec else 0, spec_k=4,
                            max_seq=256))
        await engine.start()
        try:
            global_metrics.reset()
            out = await _collect(engine, REP, max_new=96)
            accepted = global_metrics.counter(
                "engine_spec_accepted_tokens_total")
            return out, accepted
        finally:
            await engine.stop()

    plain, _ = asyncio.run(run(False))
    spec, accepted = asyncio.run(run(True))
    assert spec == plain, "speculation changed greedy output"
    assert accepted > 0, "repetitive stream never accepted a proposal"


@slow
def test_stochastic_rows_identical_under_spec():
    """Seeded stochastic requests accept nothing — their samples must be
    bit-identical with and without speculation in the engine."""
    async def run(spec):
        engine = InferenceEngine(
            engine_cfg=_cfg(spec_ngram=3 if spec else 0))
        await engine.start()
        try:
            return await _collect(engine, REP, temperature=0.8, seed=9)
        finally:
            await engine.stop()

    assert asyncio.run(run(True)) == asyncio.run(run(False))


@slow
def test_mixed_batch_and_stops_under_spec():
    """Concurrent greedy + stochastic + string-stop requests under spec:
    every stream equals its plain-engine counterpart."""
    async def run(spec):
        engine = InferenceEngine(
            engine_cfg=_cfg(spec_ngram=3 if spec else 0))
        await engine.start()
        try:
            outs = await asyncio.gather(
                _collect(engine, REP),
                _collect(engine, REP, temperature=1.1, seed=4),
                _collect(engine, list(b"xyxyxyxyxyxy"), max_new=10),
                _collect(engine, REP, max_new=3),
            )
            return outs
        finally:
            await engine.stop()

    assert asyncio.run(run(True)) == asyncio.run(run(False))


@slow
def test_spec_respects_stop_ids_and_logprobs_fallback():
    async def run():
        engine = InferenceEngine(engine_cfg=_cfg(spec_ngram=3))
        await engine.start()
        try:
            # stop token mid-acceptance: surplus accepted tokens dropped.
            plain = InferenceEngine(engine_cfg=_cfg())
            await plain.start()
            a = []
            async for ev in engine.generate(REP, max_new_tokens=20):
                a.append((ev.token_id, ev.finish_reason))
            b = []
            async for ev in plain.generate(REP, max_new_tokens=20):
                b.append((ev.token_id, ev.finish_reason))
            assert a == b
            # a logprobs request sends the batch down the plain path and
            # still gets its logprobs.
            evs = []
            async for ev in engine.generate(REP, max_new_tokens=4,
                                            stop_ids=(), logprobs=2):
                evs.append(ev)
            assert all(ev.logprob is not None for ev in evs)
            await plain.stop()
        finally:
            await engine.stop()

    asyncio.run(run())
