"""ISSUE 16: tiered KV spill + the memory-pressure degradation contract.

Five contracts:

1. **The spill tier is a pure latency optimization**: spill-on and
   spill-off token streams are byte-identical at EVERY kv mode (none,
   int8, int4) — shadows are exact pool-byte copies, stale shadows are
   dropped on fresh inserts, and any suspect page (chaos fail, corrupt,
   pin mismatch) falls back to tail re-prefill instead of splicing.
2. **Tier bookkeeping is exact**: spill_plan is deterministic and honors
   exclusion, note_spilled rejects evicted-mid-copy and duplicate pages,
   eviction MIGRATES GreedyDual accounting onto the shadow, the two-phase
   page-in claim/commit/abort protocol never leaks a pool slot, and a
   fresh insert under a spilled key supersedes the shadow.
3. **Spill chaos is two-run deterministic**: the seeded fault schedule
   consumes its RNG draws in a fixed order per I/O op, so two runs under
   the same spec record identical ``faults`` oracles (the `make chaos`
   idiom, applied to tier I/O).
4. **Degradation is typed**: both tiers exhausted -> admission verdict
   "memory" (the ERROR_CODES entry behind the 429 + Retry-After).
5. **Residency snapshots round-trip**: export_state carries the
   GreedyDual clock row and idx=-1 tier markers; import restores the
   clock and SKIPS the markers (host bytes died with the process).

Pure-host index/chaos tests run in tier-1; engine tests (jit compiles)
are slow-tier like the rest of the prefix-cache suite.
"""

import asyncio
import random

import numpy as np
import pytest

from p2p_llm_tunnel_tpu.engine.prefix_cache import (
    PagePinError,
    PrefixIndex,
    page_checksum,
    verify_page_pin,
)
from p2p_llm_tunnel_tpu.transport.chaos import (
    ChaosSpec,
    ChaosSpecError,
    SpillChaos,
    maybe_spill_chaos,
)


def _key(n: int) -> bytes:
    return n.to_bytes(16, "big")


def _payload(seed: int):
    rng = np.random.default_rng(seed)
    return {"k": rng.integers(0, 255, size=(4, 16), dtype=np.uint8),
            "v": rng.integers(0, 255, size=(4, 16), dtype=np.uint8)}


def _spill(idx: PrefixIndex, key: bytes, seed: int = 0) -> bool:
    p = _payload(seed)
    return idx.note_spilled(key, p, page_checksum(p), {"kv_quant": "none"})


# ---------------------------------------------------------------------------
# pin check + checksum (the TC18 boundary primitives)
# ---------------------------------------------------------------------------

def test_verify_page_pin_passes_and_returns_page():
    page = object()
    meta = {"kv_quant": "int4", "quant_group": 32}
    assert verify_page_pin(page, meta, {"kv_quant": "int4"}) is page
    assert verify_page_pin(page, meta, {}) is page  # nothing pinned


def test_verify_page_pin_raises_on_any_mismatch():
    meta = {"kv_quant": "int4", "quant_group": 32}
    with pytest.raises(PagePinError):
        verify_page_pin(object(), meta, {"kv_quant": "int8"})
    with pytest.raises(PagePinError):
        # A pin the page never recorded counts as a mismatch, not a pass:
        # absent metadata must not splice into a pool that pins it.
        verify_page_pin(object(), {}, {"kv_quant": "none"})


def test_page_checksum_catches_byte_flip_and_leaf_swap():
    p = _payload(1)
    ck = page_checksum(p)
    assert ck == page_checksum({k: v.copy() for k, v in p.items()})
    flipped = {k: v.copy() for k, v in p.items()}
    flipped["k"].reshape(-1)[5] ^= 0xFF
    assert page_checksum(flipped) != ck
    # Leaf-name keying: swapping two equal-shaped leaves changes the
    # digest even though the concatenated bytes are a permutation.
    swapped = {"k": p["v"], "v": p["k"]}
    assert page_checksum(swapped) != ck


# ---------------------------------------------------------------------------
# spill_plan / note_spilled / make-room (host-pure)
# ---------------------------------------------------------------------------

def test_spill_plan_lowest_prio_first_and_exclude():
    idx = PrefixIndex(16, 5, evict="cost", spill_pages=4)
    idx.allocate([_key(1)], costs=[30.0])
    idx.allocate([_key(2)], costs=[1.0])
    idx.allocate([_key(3)], costs=[10.0])
    plan = idx.spill_plan(2)
    assert [k for k, _ in plan] == [_key(2), _key(3)]  # prio order
    assert plan == idx.spill_plan(2)  # planning mutates nothing
    # Exclusion protects pages about to be matched this iteration.
    plan = idx.spill_plan(2, exclude=frozenset({_key(2)}))
    assert [k for k, _ in plan] == [_key(3), _key(1)]
    # Already-shadowed pages never re-enter the plan.
    assert _spill(idx, _key(2))
    assert [k for k, _ in idx.spill_plan(3)] == [_key(3), _key(1)]


def test_spill_plan_off_when_tier_disabled():
    idx = PrefixIndex(16, 4, evict="cost", spill_pages=0)
    idx.allocate([_key(1)], costs=[1.0])
    assert idx.spill_plan(4) == []


def test_note_spilled_rejects_evicted_and_duplicate_pages():
    idx = PrefixIndex(16, 4, evict="lru", spill_pages=4)
    idx.allocate([_key(1), _key(2)], costs=[1.0, 2.0])
    assert not _spill(idx, _key(9))  # never resident: evicted mid-copy
    assert _spill(idx, _key(1))
    assert not _spill(idx, _key(1))  # already shadowed
    assert idx.spill_pageouts == 1
    assert idx.spill_resident == 1


def test_spill_make_room_drops_resident_shadows_before_host_only():
    idx = PrefixIndex(16, 6, evict="lru", spill_pages=2)
    idx.allocate([_key(1), _key(2), _key(3)], costs=[1.0, 2.0, 3.0])
    assert _spill(idx, _key(1)) and _spill(idx, _key(2))
    # Make key 1 host-only (its shadow is now the sole body) while key
    # 2's shadow still duplicates a resident page.
    idx._evict_one({_key(2), _key(3)})
    assert idx.id_of(_key(1)) is None
    # Tier full: spilling key 3 must drop key 2's shadow (resident -> a
    # copy still lives in HBM, nothing is lost), NEVER key 1's sole body.
    assert _spill(idx, _key(3))
    assert _key(1) in idx._spill
    assert _key(2) not in idx._spill
    assert idx.spill_drops == 1


def test_eviction_migrates_greedydual_accounting_onto_shadow():
    idx = PrefixIndex(16, 4, evict="cost", spill_pages=4)
    idx.allocate([_key(1)], costs=[7.0], conv=True)
    assert _spill(idx, _key(1))
    entry = idx._lru[_key(1)]
    idx._evict_one(set())
    page = idx._spill[_key(1)]
    assert (page.cost, page.conv, page.prio) == (
        entry.cost, entry.conv, entry.prio
    )


# ---------------------------------------------------------------------------
# two-phase page-in + stale-shadow supersession (host-pure)
# ---------------------------------------------------------------------------

def test_page_in_claim_commit_restores_residency_and_keeps_shadow():
    idx = PrefixIndex(16, 4, evict="lru", spill_pages=4)
    idx.allocate([_key(1), _key(2), _key(3)], costs=[5.0, 1.0, 1.0])
    assert _spill(idx, _key(1))
    assert idx.reserve(1) == 1  # evicts key 1 (LRU) -> host-only
    idx.release(1)
    assert idx.id_of(_key(1)) is None
    claims = idx.page_in_alloc([_key(1)])
    assert len(claims) == 1
    key, slot, page = claims[0]
    assert key == _key(1) and page.cost == 5.0
    # The claimed slot is invisible until commit: not free, not indexed.
    assert slot not in idx._free
    idx.commit_page_in(key, slot)
    assert idx.id_of(_key(1)) == slot
    assert idx.spill_pageins == 1
    # The shadow stays: its bytes still match the pool copy, so the next
    # eviction migrates without another executor copy.
    assert _key(1) in idx._spill


def test_page_in_abort_returns_slot_and_drops_suspect_page():
    idx = PrefixIndex(16, 4, evict="lru", spill_pages=4)
    idx.allocate([_key(1), _key(2), _key(3)], costs=[1.0, 1.0, 1.0])
    assert _spill(idx, _key(1))
    idx.reserve(1)
    idx.release(1)
    free_before = idx.free_blocks
    (key, slot, _page), = idx.page_in_alloc([_key(1)])
    idx.abort_page_in(key, slot)
    assert idx.free_blocks == free_before  # slot returned
    assert _key(1) not in idx._spill  # suspect bytes never splice
    assert idx.spill_drops == 1
    assert idx.spill_pageins == 0
    # Aborted = gone: a retry finds nothing to page in (re-prefill owns
    # correctness from here).
    assert idx.page_in_alloc([_key(1)]) == []


def test_page_in_alloc_never_evicts_protected_chain():
    idx = PrefixIndex(16, 3, evict="lru", spill_pages=4)
    idx.allocate([_key(1), _key(2)], costs=[1.0, 1.0])
    assert _spill(idx, _key(2))
    idx._free.append(idx._evict_one({_key(1)}))  # key 2 -> host-only
    idx.allocate([_key(3)], costs=[1.0])  # pool full again: keys 1, 3
    # Page key 2 back while everything resident is protected (the
    # splicing chain's own pages): the claim must give up rather than
    # evict a protected page.
    assert idx.page_in_alloc([_key(2)],
                             protect=frozenset({_key(1), _key(3)})) == []
    assert idx.id_of(_key(1)) is not None
    assert idx.id_of(_key(3)) is not None
    # Loosen the protection: the claim now succeeds by evicting key 1.
    (k, slot, _p), = idx.page_in_alloc([_key(2)],
                                       protect=frozenset({_key(3)}))
    idx.commit_page_in(k, slot)
    assert idx.id_of(_key(2)) == slot
    assert idx.id_of(_key(1)) is None


def test_fresh_insert_supersedes_stale_shadow_and_counts_thrash():
    idx = PrefixIndex(16, 4, evict="lru", spill_pages=4)
    idx.allocate([_key(1), _key(2), _key(3)], costs=[1.0, 1.0, 1.0])
    assert _spill(idx, _key(1))
    idx.reserve(1)  # evicts key 1; its key enters _recent_evicted
    idx.release(1)
    drops = idx.spill_drops
    idx.allocate([_key(1)], costs=[1.0])  # re-prefill lands fresh bytes
    # The stale shadow would splice pre-eviction bytes over the fresh
    # insert — it must die with the insert, and the quick round-trip is
    # exactly the reuse-distance-over-capacity event the detector counts.
    assert _key(1) not in idx._spill
    assert idx.spill_drops == drops + 1
    assert idx.thrash_reallocs == 1


# ---------------------------------------------------------------------------
# residency snapshots (clock row + tier markers)
# ---------------------------------------------------------------------------

def test_export_state_clock_row_and_tier_markers():
    idx = PrefixIndex(16, 4, evict="cost", spill_pages=4)
    idx.allocate([_key(1), _key(2), _key(3)], costs=[10.0, 1.0, 5.0])
    assert _spill(idx, _key(2))
    idx.reserve(1)  # pool full: evicts key 2 (cheapest), clock rises
    idx.release(1)
    state = idx.export_state()
    assert state[0][0] == "clock" and state[0][1] > 0
    marker = [row for row in state if row[1] == -1]
    assert [row[0] for row in marker] == [_key(2).hex()]
    # Restore: clock survives, residents return, markers are SKIPPED —
    # the host-tier bytes died with the writing process.
    idx2 = PrefixIndex(16, 4, evict="cost", spill_pages=4)
    idx2.import_state(state)
    assert idx2._clock == state[0][1]
    assert idx2.id_of(_key(1)) is not None
    assert idx2.id_of(_key(2)) is None
    assert idx2.spill_resident == 0
    # Residents re-export identically (markers are gone by design).
    assert [r for r in idx2.export_state() if r[1] != -1] == [
        r for r in state if r[1] != -1
    ]


def test_import_state_still_accepts_legacy_shapes():
    idx = PrefixIndex(16, 4, spill_pages=2)
    idx.import_state([
        [_key(1).hex(), 1],                      # pre-ISSUE-14 2-field
        [_key(2).hex(), 2, 3.0, 1],              # ISSUE-14 4-field
        ["clock", 5.5],                          # ISSUE-16 clock row
        [_key(3).hex(), -1, 1.0, 0, 1.0],        # tier marker: skipped
        ["garbage"],                             # damaged: skipped
    ])
    assert idx.used_blocks == 2
    assert idx._clock == 5.5
    assert idx.free_blocks == 1


# ---------------------------------------------------------------------------
# seeded spill chaos (two-run identity, fixed draw order)
# ---------------------------------------------------------------------------

def _chaos_run(spec: str, ops: int = 40):
    ch = SpillChaos(ChaosSpec.parse(spec))
    seq = [ch.draw("pagein" if i % 3 else "pageout") for i in range(ops)]
    return ch.faults, seq


def test_spill_chaos_two_run_fault_identity_seeded():
    spec5 = "drop=0.3,corrupt=0.3,stall=0.2:0.001,seed=5"
    spec19 = "drop=0.3,corrupt=0.3,stall=0.2:0.001,seed=19"
    assert _chaos_run(spec5) == _chaos_run(spec5)
    assert _chaos_run(spec19) == _chaos_run(spec19)
    faults5, _ = _chaos_run(spec5)
    faults19, _ = _chaos_run(spec19)
    assert faults5 != faults19  # seeds exercise different schedules
    assert faults5, "p=0.8 over 40 ops drew no faults — schedule broken"


def test_spill_chaos_draw_order_is_fixed_per_op():
    """Every op consumes (r_fail, r_corrupt, r_stall, corrupt_pos) in
    that order REGARDLESS of which fault fires — the invariant that makes
    op N's draw independent of op N-1's outcome, i.e. the whole reason
    two runs line up.  Pinned by replaying the RNG by hand."""
    spec = ChaosSpec.parse("drop=0.5,corrupt=0.5,stall=0.5:0.002,seed=3")
    ch = SpillChaos(spec)
    got = [ch.draw("pageout") for _ in range(30)]
    rng = random.Random(3)
    want = []
    for _ in range(30):
        r_fail = rng.random()
        r_corrupt = rng.random()
        r_stall = rng.random()
        pos = rng.randrange(1 << 30)
        if r_fail < 0.5:
            want.append(("fail", 0.0, pos))
        elif r_corrupt < 0.5:
            want.append(("corrupt", 0.0, pos))
        elif r_stall < 0.5:
            want.append(("stall", 0.002, pos))
        else:
            want.append((None, 0.0, pos))
    assert got == want


def test_maybe_spill_chaos_env_gate(monkeypatch):
    monkeypatch.delenv("TUNNEL_SPILL_CHAOS", raising=False)
    assert maybe_spill_chaos() is None
    monkeypatch.setenv("TUNNEL_SPILL_CHAOS", "corrupt=0.5,seed=1")
    ch = maybe_spill_chaos()
    assert isinstance(ch, SpillChaos) and ch.spec.corrupt == 0.5
    with pytest.raises(ChaosSpecError):
        maybe_spill_chaos("corrupt=lots")  # malformed refuses loudly


# ---------------------------------------------------------------------------
# engine-level composition (slow: jit compiles)
# ---------------------------------------------------------------------------

def _cfg(**kw):
    from p2p_llm_tunnel_tpu.engine.engine import EngineConfig

    base = dict(model="tiny", num_slots=4, max_seq=128, dtype="float32",
                min_prefill_bucket=16, decode_steps=4, mux=True,
                prefix_cache=True, prefill_chunk=16)
    base.update(kw)
    return EngineConfig(**base)


def _turns(cfg, prompts, max_new=6):
    """SEQUENTIAL turns (unlike test_paged_pool's concurrent herd): the
    A/B/A conversation shape that forces eviction between visits, so a
    returning prompt's pages can only come back via the spill tier."""
    from p2p_llm_tunnel_tpu.engine.engine import InferenceEngine

    async def main():
        eng = InferenceEngine(engine_cfg=cfg)
        await eng.start()
        try:
            streams = []
            for p in prompts:
                out = []
                async for ev in eng.generate(p, max_new_tokens=max_new,
                                             stop_ids=()):
                    out.append(ev.token_id)
                streams.append(out)
                # Idle iterations so the end-of-iteration spill drain
                # pages the finished turn's cold pages out.
                await asyncio.sleep(0.05)
            return streams, eng
        finally:
            await eng.stop()

    return asyncio.run(main())


def _aba_prompts():
    a = list(range(1, 52)) + [400]
    b = list(range(100, 151)) + [401]
    c = list(range(200, 251)) + [402]
    return [a, b, c, a]  # B+C evict A's pages; A's return pages them in


@pytest.mark.slow
@pytest.mark.parametrize("kv_quant", ["none", "int8", "int4"])
def test_spill_on_off_identity_every_kv_mode(kv_quant):
    """ISSUE 16 acceptance: the host tier is a pure latency optimization
    — spill-on and spill-off token streams are byte-identical at every
    kv mode, while the spill path demonstrably ran (pages out AND back
    in on the A/B/A return)."""
    prompts = _aba_prompts()
    cfg = dict(kv_quant=kv_quant, prefix_pool_blocks=6, prefix_evict="cost")
    off, _ = _turns(_cfg(spill_pages=0, **cfg), prompts)
    on, eng = _turns(_cfg(spill_pages=8, **cfg), prompts)
    assert on == off, f"spill tier changed the stream under kv_quant={kv_quant}"
    assert eng._prefix.spill_pageouts > 0
    assert eng._prefix.spill_pageins > 0, "A's return never touched the tier"


@pytest.mark.slow
def test_spill_chaos_corrupt_pagein_falls_back_byte_identical(monkeypatch):
    """Seeded corrupt=1.0 chaos poisons EVERY page-in copy: the checksum
    must catch each one, abort the splice, and fall back to tail
    re-prefill — with a stream byte-identical to the unfaulted run."""
    prompts = _aba_prompts()
    cfg = dict(prefix_pool_blocks=6, prefix_evict="cost", spill_pages=8)
    monkeypatch.delenv("TUNNEL_SPILL_CHAOS", raising=False)
    clean, _ = _turns(_cfg(**cfg), prompts)
    monkeypatch.setenv("TUNNEL_SPILL_CHAOS", "corrupt=1.0,seed=5")
    faulted, eng = _turns(_cfg(**cfg), prompts)
    assert faulted == clean, "corrupt page-in leaked into the stream"
    kinds = {k for _, op, k in eng._spill_chaos.faults if op == "pagein"}
    assert kinds == {"corrupt"}
    assert eng._prefix.spill_pageins == 0  # every splice was refused


@pytest.mark.slow
def test_spill_chaos_two_run_engine_fault_identity(monkeypatch):
    """The `make chaos` two-run oracle at engine level: identical seeded
    runs record identical tier fault schedules AND identical streams."""
    prompts = _aba_prompts()
    cfg = dict(prefix_pool_blocks=6, prefix_evict="cost", spill_pages=8)

    def run(seed):
        monkeypatch.setenv(
            "TUNNEL_SPILL_CHAOS", f"drop=0.4,corrupt=0.4,seed={seed}"
        )
        streams, eng = _turns(_cfg(**cfg), prompts)
        return streams, eng._spill_chaos.faults

    s1, f1 = run(5)
    s2, f2 = run(5)
    assert (s1, f1) == (s2, f2)
    monkeypatch.delenv("TUNNEL_SPILL_CHAOS", raising=False)
    clean, _ = _turns(_cfg(**cfg), prompts)
    assert s1 == clean  # every fault degraded to re-prefill, not bytes


@pytest.mark.slow
def test_memory_exhaustion_admission_verdict():
    """Degradation contract: both tiers exhausted -> admission_check
    returns the typed "memory" verdict (the 429 + Retry-After code)
    before any queue arithmetic, and counts the shed."""
    from p2p_llm_tunnel_tpu.engine.engine import InferenceEngine
    from p2p_llm_tunnel_tpu.engine.prefix_cache import _SpillPage
    from p2p_llm_tunnel_tpu.utils.metrics import global_metrics

    eng = InferenceEngine(engine_cfg=_cfg(spill_pages=2, max_waiting=64))
    # Deliberately NOT started (the fair-admission idiom): verdicts are
    # pure host reads over index state.
    pi = eng._prefix
    assert eng.admission_check(1) is None
    pi.reserved_pages = pi.capacity - 1  # HBM fully reserved
    assert eng.admission_check(1) is None  # spill tier still has room
    for n in range(pi.spill_pages):
        pi._spill[_key(n)] = _SpillPage({}, b"", {})
    before = global_metrics.counter("engine_memory_shed_total")
    assert eng.admission_check(1) == "memory"
    assert global_metrics.counter("engine_memory_shed_total") == before + 1
    assert eng.retry_after_s() >= 1.0
    pi.reserved_pages = 0
    assert eng.admission_check(1) is None  # pressure gone, verdict gone


@pytest.mark.slow
def test_spill_fenced_without_prefix_cache():
    """spill_pages>0 with prefix_cache=False auto-disables WITH a
    recorded fence (the ISSUE 14 config_fences contract), because the
    tier shadows pool pages that don't exist."""
    from p2p_llm_tunnel_tpu.engine.engine import InferenceEngine

    eng = InferenceEngine(engine_cfg=_cfg(prefix_cache=False, spill_pages=8))
    assert eng.ecfg.spill_pages == 0
    assert any(f["knob"] == "spill_pages" for f in eng.config_fences)
