"""Cross-host SPMD serving (PARITY A8): 2-process CPU proof.

Two real OS processes join a jax.distributed runtime (Gloo CPU
collectives), build IDENTICAL engines over a tp=2 mesh that SPANS the
processes (one CPU device each), and serve: rank 0 drives generation
through the normal engine loop while rank 1 replays the broadcast
dispatch stream (InferenceEngine.spmd_follower_loop).  The tokens rank 0
emits must equal a single-process tp=2 oracle — proving the follower
executed every collective in lockstep (a desync deadlocks or corrupts).

Subprocess-based like the transport-net suite: multi-controller JAX
cannot be simulated in one process.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: One script, two ranks.  Greedy sampling (temperature 0) + fixed seed so
#: the oracle comparison is exact.
WORKER = textwrap.dedent("""\
    import asyncio, json, os, sys

    rank = int(sys.argv[1])
    port = sys.argv[2]
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(
        f"127.0.0.1:{port}", num_processes=2, process_id=rank
    )
    import numpy as np
    from jax.sharding import Mesh
    from p2p_llm_tunnel_tpu.engine.engine import EngineConfig, InferenceEngine
    from p2p_llm_tunnel_tpu.models.config import get_config
    from p2p_llm_tunnel_tpu.parallel.mesh import AXES

    mesh = Mesh(
        np.array(jax.devices()).reshape(1, 1, 2, 1), AXES
    )  # tp=2 across the two processes
    engine = InferenceEngine(
        model_cfg=get_config("tiny", n_heads=8, n_kv_heads=2, vocab_size=512),
        engine_cfg=EngineConfig(
            model="tiny", num_slots=2, max_seq=64, dtype="float32",
            seed=0, decode_steps=4, decode_steps_eager=0, prefill_rows=2,
            prefix_cache=True, prefix_pool_blocks=8, min_prefill_bucket=16,
        ),
        mesh=mesh,
    )

    async def lead():
        await engine.start()
        outs = []
        for prompt in (list(range(1, 25)), list(range(1, 25)), [9, 8, 7]):
            toks = []
            async for ev in engine.generate(
                prompt, max_new_tokens=6, stop_ids=()
            ):
                toks.append(ev.token_id)
            outs.append(toks)
        await engine.stop()
        assert engine._prefix.hits >= 1, "prefix cache never hit"
        print("RESULT " + json.dumps(outs), flush=True)

    if rank == 0:
        asyncio.run(lead())
    else:
        engine.spmd_follower_loop()
""")

ORACLE = textwrap.dedent("""\
    import asyncio, json, os
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    from jax.sharding import Mesh
    from p2p_llm_tunnel_tpu.engine.engine import EngineConfig, InferenceEngine
    from p2p_llm_tunnel_tpu.models.config import get_config
    from p2p_llm_tunnel_tpu.parallel.mesh import AXES

    mesh = Mesh(np.array(jax.devices()[:2]).reshape(1, 1, 2, 1), AXES)
    engine = InferenceEngine(
        model_cfg=get_config("tiny", n_heads=8, n_kv_heads=2, vocab_size=512),
        engine_cfg=EngineConfig(
            model="tiny", num_slots=2, max_seq=64, dtype="float32",
            seed=0, decode_steps=4, decode_steps_eager=0, prefill_rows=2,
            prefix_cache=True, prefix_pool_blocks=8, min_prefill_bucket=16,
        ),
        mesh=mesh,
    )

    async def run():
        await engine.start()
        outs = []
        for prompt in (list(range(1, 25)), list(range(1, 25)), [9, 8, 7]):
            toks = []
            async for ev in engine.generate(
                prompt, max_new_tokens=6, stop_ids=()
            ):
                toks.append(ev.token_id)
            outs.append(toks)
        await engine.stop()
        print("RESULT " + json.dumps(outs), flush=True)

    asyncio.run(run())
""")


def _run(script: str, *argv: str) -> subprocess.Popen:
    env = dict(os.environ, PYTHONPATH=REPO)
    env.pop("XLA_FLAGS", None)
    return subprocess.Popen(
        [sys.executable, "-c", script, *argv],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        cwd=REPO,
    )


def _result_of(out: bytes):
    for line in out.decode().splitlines():
        if line.startswith("RESULT "):
            return json.loads(line[len("RESULT "):])
    return None


def _free_port() -> str:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return str(s.getsockname()[1])


@pytest.mark.slow
def test_two_process_spmd_serving_matches_oracle():
    port = _free_port()
    lead = _run(WORKER, "0", port)
    follow = _run(WORKER, "1", port)
    try:
        out0, err0 = lead.communicate(timeout=600)
        out1, err1 = follow.communicate(timeout=60)
    finally:
        for p in (lead, follow):
            if p.poll() is None:
                p.kill()
    assert lead.returncode == 0, err0.decode()[-2000:]
    assert follow.returncode == 0, err1.decode()[-2000:]
    tokens = _result_of(out0)
    assert tokens is not None, out0.decode()[-500:]

    oracle_p = _run(ORACLE)
    out_o, err_o = oracle_p.communicate(timeout=600)
    assert oracle_p.returncode == 0, err_o.decode()[-2000:]
    expected = _result_of(out_o)
    assert tokens == expected, (tokens, expected)
