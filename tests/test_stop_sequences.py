"""String stop sequences (OpenAI `stop`, Ollama `options.stop`) at the API
layer: boundary-safe matching, held-prefix flushing, and end-to-end
truncation through the engine."""

import asyncio
import json

from p2p_llm_tunnel_tpu.engine.api import EngineAPI, _StopMatcher
from p2p_llm_tunnel_tpu.engine.engine import EngineConfig, InferenceEngine
from p2p_llm_tunnel_tpu.protocol.frames import RequestHeaders

import pytest

# Compile-heavy (JAX jit of engine/model programs): excluded from
# `make test-fast` (VERDICT r4 item 8).
pytestmark = pytest.mark.slow


# ---------------------------------------------------------------------------
# _StopMatcher
# ---------------------------------------------------------------------------

def test_matcher_passthrough_without_stops():
    m = _StopMatcher([])
    assert m.feed("hello") == ("hello", False)


def test_matcher_simple_hit():
    m = _StopMatcher(["STOP"])
    assert m.feed("abcSTOPdef") == ("abc", True)


def test_matcher_stop_spanning_chunks():
    m = _StopMatcher(["END"])
    out1, hit1 = m.feed("abcE")
    assert (out1, hit1) == ("abc", False)  # 'E' held: could start 'END'
    out2, hit2 = m.feed("N")
    assert (out2, hit2) == ("", False)  # 'EN' still a prefix
    out3, hit3 = m.feed("D tail")
    assert (out3, hit3) == ("", True)  # completed: nothing after emits


def test_matcher_false_prefix_flushes():
    m = _StopMatcher(["END"])
    assert m.feed("abcE") == ("abc", False)
    assert m.feed("xyz") == ("Exyz", False)  # 'E' was not a stop after all


def test_matcher_earliest_of_multiple_stops_wins():
    m = _StopMatcher(["ZZ", "B"])
    assert m.feed("aBcZZ") == ("a", True)


def test_matcher_flush_returns_held_tail():
    m = _StopMatcher(["LONGSTOP"])
    out, hit = m.feed("xLONGSTO")
    assert (out, hit) == ("x", False)
    assert m.flush() == "LONGSTO"


# ---------------------------------------------------------------------------
# end-to-end through the engine API
# ---------------------------------------------------------------------------

def _api():
    eng = InferenceEngine(engine_cfg=EngineConfig(
        model="tiny", num_slots=2, max_seq=128, dtype="float32",
    ))
    return EngineAPI(eng, "tiny"), eng


def _req(path, body):
    return RequestHeaders(1, "POST", path, {}), json.dumps(body).encode()


async def _collect_sse(chunks):
    events = []
    async for chunk in chunks:
        for event in chunk.decode().split("\n\n"):
            if event.startswith("data: ") and event != "data: [DONE]":
                events.append(json.loads(event[6:]))
    return events


def test_stop_string_truncates_openai_completion():
    async def run():
        api, eng = _api()
        await eng.start()
        # Learn the unstopped greedy text first, then stop on a substring
        # drawn from its middle.
        req, body = _req("/v1/completions", {
            "prompt": "hello", "max_tokens": 12, "ignore_eos": True,
        })
        _, _, chunks = await api.handle(req, body)
        full = json.loads([c async for c in chunks][0])
        text = full["choices"][0]["text"]
        assert len(text) > 4
        stop = text[3:5]
        req, body = _req("/v1/completions", {
            "prompt": "hello", "max_tokens": 12, "ignore_eos": True,
            "stop": stop,
        })
        _, _, chunks = await api.handle(req, body)
        stopped = json.loads([c async for c in chunks][0])
        choice = stopped["choices"][0]
        await eng.stop()
        assert stop not in choice["text"]
        assert text.startswith(choice["text"])
        assert choice["finish_reason"] == "stop"
        return True

    assert asyncio.run(run())


def test_stop_string_truncates_sse_stream():
    async def run():
        api, eng = _api()
        await eng.start()
        req, body = _req("/v1/chat/completions", {
            "messages": [{"role": "user", "content": "hi"}],
            "max_tokens": 12, "ignore_eos": True, "stream": True,
        })
        _, _, chunks = await api.handle(req, body)
        events = await _collect_sse(chunks)
        full = "".join(
            e["choices"][0]["delta"].get("content", "") for e in events
        )
        stop = full[3:5]
        req, body = _req("/v1/chat/completions", {
            "messages": [{"role": "user", "content": "hi"}],
            "max_tokens": 12, "ignore_eos": True, "stream": True,
            "stop": [stop],
        })
        _, _, chunks = await api.handle(req, body)
        events = await _collect_sse(chunks)
        await eng.stop()
        text = "".join(
            e["choices"][0]["delta"].get("content", "") for e in events
        )
        assert stop not in text and full.startswith(text)
        finishes = [e["choices"][0]["finish_reason"] for e in events]
        assert finishes[-1] == "stop"
        return True

    assert asyncio.run(run())


def test_ollama_options_stop():
    async def run():
        api, eng = _api()
        await eng.start()
        req, body = _req("/api/generate", {
            "prompt": "hi", "max_new_tokens": 12, "ignore_eos": True,
            "stream": False,
        })
        _, _, chunks = await api.handle(req, body)
        full = json.loads([c async for c in chunks][0])["response"]
        stop = full[2:4]
        req, body = _req("/api/generate", {
            "prompt": "hi", "max_new_tokens": 12, "ignore_eos": True,
            "stream": False, "options": {"stop": [stop]},
        })
        _, _, chunks = await api.handle(req, body)
        resp = json.loads([c async for c in chunks][0])
        await eng.stop()
        assert stop not in resp["response"]
        assert resp["done_reason"] == "stop"
        return True

    assert asyncio.run(run())


def test_invalid_stop_rejected_before_stream():
    async def run():
        api, eng = _api()
        await eng.start()
        req, body = _req("/v1/completions", {
            "prompt": "x", "stop": 42,
        })
        status, _, _ = await api.handle(req, body)
        await eng.stop()
        return status

    assert asyncio.run(run()) == 400
