"""Tunnel-wide request tracing (ISSUE 6): context propagation, the span
journal, Chrome-trace export, /metrics exposition, and tail percentiles.

Three layers, matching where the machinery lives:
- pure recorder/registry logic (utils/tracing.py, utils/metrics.py) — no
  asyncio, no JAX;
- serve-endpoint surfaces over a loopback channel with a FAKE backend
  (/metrics exposition, /healthz?trace=1, span parenting across the
  header rewrite) — fast;
- engine-backed behavior: a 32-client mux herd whose every request's
  spans chain proxy -> serve -> engine under one propagated trace id, and
  a seeded-chaos topology-determinism run — JAX compiles, slow.
"""

from __future__ import annotations

import asyncio
import contextlib
import importlib.util
import json
import os
from pathlib import Path

import pytest

from p2p_llm_tunnel_tpu.endpoints.serve import run_serve
from p2p_llm_tunnel_tpu.testing.frame_client import FrameClient
from p2p_llm_tunnel_tpu.transport import loopback_pair
from p2p_llm_tunnel_tpu.utils.metrics import (
    METRICS_CATALOG,
    Metrics,
    _Percentiles,
    global_metrics,
)
from p2p_llm_tunnel_tpu.utils.tracing import (
    SPAN_CATALOG,
    TRACE_HEADER,
    TraceContext,
    TraceRecorder,
    global_tracer,
    mint_trace_id,
    new_span_id,
    parse_trace_context,
    validate_chrome_trace,
)

REPO_ROOT = Path(__file__).resolve().parents[1]
TID = "deadbeef" * 4


@contextlib.contextmanager
def tracing_on(sample: float = 1.0, capacity: int = 16384):
    """Enable the process-wide recorder for one test, restore after."""
    global_tracer.clear()
    global_tracer.configure(enabled=True, sample=sample, capacity=capacity)
    try:
        yield global_tracer
    finally:
        global_tracer.configure(enabled=False, sample=1.0)
        global_tracer.clear()


# ---------------------------------------------------------------------------
# trace context: header contract
# ---------------------------------------------------------------------------

def test_header_roundtrip():
    tid = mint_trace_id()
    ctx = TraceContext(tid, "00ab")
    parsed = parse_trace_context({TRACE_HEADER: ctx.header_value()})
    assert parsed == ctx
    # Case-insensitive header key, like the deadline header.
    assert parse_trace_context({"X-Tunnel-Trace": f"{tid}/1"}).trace_id == tid


@pytest.mark.parametrize("bad", [
    "", "no-slash", "/orphan", "GHIJ/1", "spaces here/1",
])
def test_malformed_header_is_ignored(bad):
    assert parse_trace_context({TRACE_HEADER: bad}) is None


def test_mint_trace_id_unique_and_hex():
    ids = {mint_trace_id() for _ in range(64)}
    assert len(ids) == 64
    assert all(len(t) == 32 and int(t, 16) >= 0 for t in ids)


# ---------------------------------------------------------------------------
# recorder: off by default, bounded, sampled
# ---------------------------------------------------------------------------

def test_recorder_disabled_by_default_records_nothing():
    rec = TraceRecorder()
    assert rec.add_span("engine.request", trace_id=TID, t0=0.0) is None
    rec.add_event("engine.first_token", trace_id=TID)
    assert rec.records() == []
    # The process-wide default is off too (production default).
    assert not global_tracer.enabled


def test_ring_buffer_stays_bounded():
    rec = TraceRecorder(capacity=8, enabled=True)
    for i in range(40):
        rec.add_span("engine.request", trace_id=TID, t0=float(i),
                     t1=float(i) + 0.5)
    recs = rec.records()
    assert len(recs) == 8
    assert recs[0].ts == 32.0  # oldest half dropped, recency kept


def test_engine_scope_firehose_cannot_evict_request_chains():
    """Engine-scope records (trace_id=None) ignore the sampling knob and
    fire every loop iteration; they get their own quarter-sized ring so a
    rare sampled request chain survives the unsampled firehose."""
    rec = TraceRecorder(capacity=64, enabled=True)
    rec.add_span("engine.request", trace_id=TID, t0=0.0, t1=1.0)
    for i in range(10_000):
        rec.add_span("engine.decode_burst", trace_id=None, t0=float(i),
                     t1=float(i) + 0.1, track="engine-loop")
    recs = rec.records()
    assert any(r.trace_id == TID for r in recs)
    assert sum(1 for r in recs if r.trace_id is None) <= 16  # cap // 4


def test_sampling_is_deterministic_per_trace_id():
    full = TraceRecorder(enabled=True, sample=1.0)
    none = TraceRecorder(enabled=True, sample=0.0)
    half_a = TraceRecorder(enabled=True, sample=0.5)
    half_b = TraceRecorder(enabled=True, sample=0.5)
    ids = [mint_trace_id() for _ in range(64)]
    assert all(full.on(t) for t in ids)
    assert not any(none.on(t) for t in ids)
    picks = [half_a.on(t) for t in ids]
    assert picks == [half_b.on(t) for t in ids]  # layer-independent verdict
    assert 0 < sum(picks) < len(ids)
    # Engine-scope records follow `enabled` only.
    assert none.on(None)


def test_chrome_trace_validates_and_carries_track_metadata():
    rec = TraceRecorder(enabled=True)
    root = rec.add_span("proxy.request", trace_id=TID, t0=1.0, t1=2.0,
                        track="proxy", attrs={"status": 200})
    rec.add_span("serve.dispatch", trace_id=TID, parent_id=root, t0=1.1,
                 t1=1.9, track="serve")
    rec.add_event("engine.first_token", trace_id=TID, t=1.5)
    rec.add_span("engine.decode_burst", trace_id=None, t0=1.2, t1=1.3,
                 track="engine-loop")
    obj = rec.chrome_trace()
    assert validate_chrome_trace(obj)
    names = {e["name"] for e in obj["traceEvents"] if e["ph"] != "M"}
    assert names == {"proxy.request", "serve.dispatch",
                     "engine.first_token", "engine.decode_burst"}
    threads = {e["args"]["name"] for e in obj["traceEvents"]
               if e["ph"] == "M" and e["name"] == "thread_name"}
    assert threads == {"proxy", "serve", "engine", "engine-loop"}
    # Parent links survive export.
    serve = next(e for e in obj["traceEvents"]
                 if e["name"] == "serve.dispatch")
    assert serve["args"]["parent_id"] == root


def test_validator_rejects_malformed_traces():
    with pytest.raises(ValueError):
        validate_chrome_trace({"traceEvents": "nope"})
    with pytest.raises(ValueError):
        validate_chrome_trace({"traceEvents": [{"name": "x", "ph": "X",
                                                "pid": 1, "tid": 1}]})
    with pytest.raises(ValueError):
        validate_chrome_trace({"traceEvents": [
            {"name": "x", "ph": "Q", "pid": 1, "tid": 1, "ts": 0}
        ]})


def test_span_catalog_names_are_layer_dotted():
    for name in SPAN_CATALOG:
        layer, _, what = name.partition(".")
        assert layer in ("proxy", "serve", "engine") and what, name


# ---------------------------------------------------------------------------
# metrics registry: tails, reservoir cap, exposition, windowed rate
# ---------------------------------------------------------------------------

def test_snapshot_carries_tail_percentiles():
    m = Metrics(hist_cap=20000)
    for i in range(10000):
        m.observe("engine_ttft_ms", float(i))
    snap = m.snapshot()
    assert snap["engine_ttft_ms_p50"] == pytest.approx(5000, abs=10)
    assert snap["engine_ttft_ms_p99"] == pytest.approx(9900, abs=15)
    assert snap["engine_ttft_ms_p999"] == pytest.approx(9990, abs=15)
    assert snap["engine_ttft_ms_count"] == 10000


def test_bad_reservoir_cap_fails_at_construction():
    """A bad TUNNEL_METRICS_RESERVOIR must fail when the registry is
    built, not at the first observe() deep inside the serving path."""
    with pytest.raises(ValueError):
        Metrics(hist_cap=1)


def test_reservoir_cap_is_configurable():
    p = _Percentiles(cap=8)
    for i in range(100):
        p.observe(float(i))
    assert p.count <= 8
    m = Metrics(hist_cap=32)
    for i in range(1000):
        m.observe("proxy_ttfb_ms", float(i))
    assert m.snapshot()["proxy_ttfb_ms_count"] <= 32


def test_prometheus_text_covers_the_full_catalog():
    m = Metrics(hist_cap=4096)
    m.inc("engine_tokens_total", 7)
    m.set_gauge("engine_queue_depth", 3)
    for i in range(100):
        m.observe("engine_ttft_ms", float(i))
    text = m.prometheus_text()
    for name in METRICS_CATALOG:
        assert f"# HELP {name} " in text, name
        assert f"# TYPE {name} " in text, name
    assert "# TYPE engine_tokens_total counter" in text
    assert "engine_tokens_total 7" in text
    assert "# TYPE engine_queue_depth gauge" in text
    assert "# TYPE engine_ttft_ms summary" in text
    for q in ("0.5", "0.95", "0.99", "0.999"):
        assert f'engine_ttft_ms{{quantile="{q}"}}' in text  # tunnelcheck: disable=TC12  read-side assertion against the registry's OWN rendering; no series is produced here
    assert "engine_ttft_ms_count 100" in text
    # Never-written series still expose zeros (schema-complete scrape).
    assert "serve_shed_total 0" in text


def test_rate_uses_a_sliding_window_and_survives_reset():
    m = Metrics()
    m.inc("engine_tokens_total", 100)
    first = m.rate("engine_tokens_total")  # lifetime fallback
    assert first >= 0
    m.inc("engine_tokens_total", 50)
    again = m.rate("engine_tokens_total", window_s=60.0)
    assert again >= 0
    # reset() drops the sample history with the counters: the next read
    # must not divide a fresh count by a stale anchor (can't go negative,
    # can't explode).
    m.reset()
    m.inc("engine_tokens_total", 10)
    post = m.rate("engine_tokens_total")
    assert post >= 0
    # Reads spaced wider than the window keep ONE out-of-window anchor:
    # the rate stays a recent-delta estimate rather than silently falling
    # back to the lifetime average every read.
    m2 = Metrics()
    m2.inc("engine_tokens_total", 5)
    m2.rate("engine_tokens_total", window_s=0.0)  # seeds the anchor
    m2.inc("engine_tokens_total", 5)
    r = m2.rate("engine_tokens_total", window_s=0.0)  # anchor is "stale"
    assert r > 0
    # The out-of-window anchor was RETAINED (old + new sample), not
    # pruned into the lifetime fallback.
    assert len(m2._rate_hist["engine_tokens_total"]) == 2


# ---------------------------------------------------------------------------
# serve endpoint surfaces over loopback (fake backend; fast)
# ---------------------------------------------------------------------------

async def _stack(backend, **serve_kwargs):
    serve_ch, client_ch = loopback_pair()
    serve_task = asyncio.create_task(
        run_serve(serve_ch, backend=backend, **serve_kwargs)
    )
    client = FrameClient(client_ch)
    await client.handshake(timeout=10.0)
    return serve_task, serve_ch, client


async def _teardown(serve_task, serve_ch, client):
    client.close()
    serve_task.cancel()
    serve_ch.close()
    await asyncio.gather(serve_task, return_exceptions=True)


def _echo_backend():
    async def chunks():
        yield b"ok"

    async def backend(req, body):
        return 200, {"content-type": "text/plain"}, chunks()

    return backend


def test_serve_metrics_endpoint_is_prometheus_text():
    async def main():
        serve_task, ch, client = await _stack(_echo_backend())
        try:
            r = await client.wait(
                await client.request("GET", "/metrics"), 10.0
            )
            assert r.status == 200
            assert r.headers["content-type"].startswith("text/plain")
            assert "# TYPE engine_tokens_total counter" in r.text
            assert "# TYPE proxy_ttfb_ms summary" in r.text
        finally:
            await _teardown(serve_task, ch, client)

    asyncio.run(main())


def test_healthz_trace_export_and_pool_accounting():
    async def main():
        with tracing_on():
            serve_task, ch, client = await _stack(_echo_backend())
            try:
                tid = mint_trace_id()
                r = await client.wait(await client.request(
                    "GET", "/work",
                    headers={TRACE_HEADER: f"{tid}/0001"},
                ), 10.0)
                assert r.status == 200
                capture = await client.wait(await client.request(
                    "GET", "/healthz?trace=1"), 10.0)
                assert capture.status == 200
                obj = json.loads(capture.text)
                assert validate_chrome_trace(obj)
                spans = {e["name"]: e for e in obj["traceEvents"]
                         if e["ph"] == "X"}
                assert spans["serve.dispatch"]["args"]["trace_id"] == tid
                # The client-sent span id is the dispatch span's parent.
                assert spans["serve.dispatch"]["args"]["parent_id"] == "0001"
                # Plain /healthz still answers, with the new tail +
                # pool-accounting sections.
                h = await client.wait(await client.request(
                    "GET", "/healthz"), 10.0)
                payload = json.loads(h.text)
                assert "ttft_p999_ms" in payload["tails"]
                assert set(payload["prefix_pool"]) == {
                    "blocks_used", "blocks_free", "kv_bytes",
                    # ISSUE 14: reservation/eviction accounting + the
                    # conversation cache's reuse counters.
                    "pages_reserved", "evictions_total", "conversation",
                    # ISSUE 16: host-RAM spill tier + the memory
                    # degradation contract's live reason.
                    "spill", "degraded_reason",
                }
                assert set(payload["prefix_pool"]["conversation"]) == {
                    "saved_pages_total", "hits_total", "hit_tokens_total",
                }
                assert set(payload["prefix_pool"]["spill"]) == {
                    "pages", "bytes", "inflight", "pageouts_total",
                    "pageins_total", "pagein_failures_total",
                    "memory_sheds_total", "thrash_trips_total",
                }
                # The composition-fence registry rides /healthz too: a
                # list (empty unless an engine auto-disabled something).
                assert isinstance(payload["config"]["fences"], list)
            finally:
                await _teardown(serve_task, ch, client)

    asyncio.run(main())


def test_untraced_request_records_nothing_even_when_enabled():
    """No x-tunnel-trace header and no proxy in front: the serve layer has
    no context to record under — the journal stays empty (no orphan
    spans), and sampling=0 drops a present header's trace too."""
    async def main():
        with tracing_on():
            serve_task, ch, client = await _stack(_echo_backend())
            try:
                await client.wait(await client.request("GET", "/x"), 10.0)
                assert [r for r in global_tracer.records()
                        if r.trace_id is not None] == []
            finally:
                await _teardown(serve_task, ch, client)
        with tracing_on(sample=0.0):
            serve_task, ch, client = await _stack(_echo_backend())
            try:
                await client.wait(await client.request(
                    "GET", "/x",
                    headers={TRACE_HEADER: f"{mint_trace_id()}/1"},
                ), 10.0)
                assert global_tracer.records() == []
            finally:
                await _teardown(serve_task, ch, client)

    asyncio.run(main())


def test_proxy_metrics_tunnels_through_and_local_answers_locally():
    """Bare /metrics through the proxy reaches the SERVE loop (in the
    deployed two-process topology that registry holds the engine_*/serve_*
    series — a local answer would render them as silent zeros), while
    /metrics?local=1 answers from the proxy process even tunnel-down."""
    from p2p_llm_tunnel_tpu.endpoints import http11
    from p2p_llm_tunnel_tpu.endpoints.proxy import run_proxy

    async def main():
        serve_ch, proxy_ch = loopback_pair()
        serve_task = asyncio.create_task(
            run_serve(serve_ch, backend=_echo_backend())
        )
        ready: asyncio.Future = asyncio.get_running_loop().create_future()
        proxy_task = asyncio.create_task(
            run_proxy(proxy_ch, "127.0.0.1", 0, ready=ready)
        )
        port = await asyncio.wait_for(ready, 10.0)
        base = f"http://127.0.0.1:{port}"
        try:
            before = global_metrics.counter("serve_requests_total")
            r = await http11.http_request("GET", f"{base}/metrics")
            body = (await r.read_all()).decode()
            assert r.status == 200
            assert "# TYPE engine_tokens_total counter" in body
            # The scrape crossed the tunnel and the serve loop answered
            # (loop-served routes don't count as backend dispatches).
            assert global_metrics.counter("serve_requests_total") == before
            rl = await http11.http_request("GET", f"{base}/metrics?local=1")
            assert rl.status == 200
            assert "# TYPE proxy_ttfb_ms summary" in (
                await rl.read_all()
            ).decode()
            # The proxy's OWN span journal is exportable too (the ingress
            # spans live in this process in the two-process topology).
            rt = await http11.http_request(
                "GET", f"{base}/healthz?trace=1&local=1"
            )
            assert rt.status == 200
            assert validate_chrome_trace(json.loads(await rt.read_all()))
        finally:
            serve_task.cancel()
            proxy_task.cancel()
            serve_ch.close()
            await asyncio.gather(serve_task, proxy_task,
                                 return_exceptions=True)

    asyncio.run(main())


# ---------------------------------------------------------------------------
# traceview summarizer
# ---------------------------------------------------------------------------

def _load_traceview():
    path = REPO_ROOT / "scripts" / "traceview.py"
    spec = importlib.util.spec_from_file_location("traceview", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_traceview_reconstructs_the_ttft_split():
    rec = TraceRecorder(enabled=True)
    root = rec.add_span("proxy.request", trace_id=TID, t0=1.0, t1=3.0,
                        track="proxy",
                        attrs={"path": "/v1/chat/completions",
                               "status": 200})
    eng = rec.add_span("engine.request", trace_id=TID, parent_id=root,
                       t0=1.1, t1=2.9, attrs={"finish": "stop"})
    rec.add_span("engine.queue_wait", trace_id=TID, parent_id=eng,
                 t0=1.1, t1=1.4)
    rec.add_span("engine.prefill_exec", trace_id=TID, parent_id=eng,
                 t0=1.4, t1=1.6)
    rec.add_event("engine.first_token", trace_id=TID, parent_id=eng, t=1.6)
    rec.add_span("engine.decode_burst", trace_id=None, t0=1.6, t1=1.8,
                 track="engine-loop")
    tv = _load_traceview()
    out = tv.summarize(rec.chrome_trace())
    (req,) = out["requests"]
    assert req["ttft_ms"] == pytest.approx(500, abs=1)
    assert req["queue_wait_ms"] == pytest.approx(300, abs=1)
    assert req["prefill_exec_ms"] == pytest.approx(200, abs=1)
    # The split tiles TTFT exactly — the reconstruction the ISSUE asks for.
    assert req["queue_wait_ms"] + req["prefill_exec_ms"] == pytest.approx(
        req["ttft_ms"], abs=1
    )
    assert out["aggregate"]["ttft_p50_ms"] == pytest.approx(500, abs=1)
    assert out["engine_scope"]["engine.decode_burst"]["count"] == 1


def test_traceview_multi_generation_trace_pairs_by_parent():
    """n>1 / prompt-list requests run several engine generations under ONE
    propagated trace id: the rollup must pair children with THEIR
    generation by parent linkage, never by span name (which would compute
    a bogus — even negative — TTFT from generation B's first token and
    generation A's span)."""
    rec = TraceRecorder(enabled=True)
    root = rec.add_span("proxy.request", trace_id=TID, t0=1.0, t1=9.0,
                        track="proxy", attrs={"status": 200})
    a = rec.add_span("engine.request", trace_id=TID, parent_id=root,
                     t0=1.0, t1=4.0)
    rec.add_span("engine.queue_wait", trace_id=TID, parent_id=a,
                 t0=1.0, t1=1.2)
    rec.add_event("engine.first_token", trace_id=TID, parent_id=a, t=1.5)
    b = rec.add_span("engine.request", trace_id=TID, parent_id=root,
                     t0=2.0, t1=9.0)
    rec.add_span("engine.queue_wait", trace_id=TID, parent_id=b,
                 t0=2.0, t1=6.0)
    rec.add_event("engine.first_token", trace_id=TID, parent_id=b, t=7.0)
    tv = _load_traceview()
    (req,) = tv.summarize(rec.chrome_trace())["requests"]
    assert req["generations"] == 2
    # First generation's numbers, not a cross-generation mixture.
    assert req["ttft_ms"] == pytest.approx(500, abs=1)
    assert req["queue_wait_ms"] == pytest.approx(200, abs=1)
    assert req["total_ms"] == pytest.approx(8000, abs=1)


def test_traceview_per_peer_attribution():
    """Fabric captures carry serve.dispatch peer attrs (ISSUE 9): the
    rollup attributes each request's TTFT to the peer whose dispatch
    parented the engine generation, lists every peer a failover touched,
    and rolls up a by_peer aggregate with a failover count."""
    rec = TraceRecorder(enabled=True)
    root = rec.add_span("proxy.request", trace_id=TID, t0=1.0, t1=3.0,
                        track="proxy", attrs={"status": 200,
                                              "peer": "peer-b"})
    rec.add_span("serve.dispatch", trace_id=TID, parent_id=root,
                 track="serve", t0=1.0, t1=1.2,
                 attrs={"peer": "peer-a", "path": "/gen"})
    d2 = rec.add_span("serve.dispatch", trace_id=TID, parent_id=root,
                      track="serve", t0=1.3, t1=2.9,
                      attrs={"peer": "peer-b", "path": "/gen"})
    eng = rec.add_span("engine.request", trace_id=TID, parent_id=d2,
                       t0=1.4, t1=2.8)
    rec.add_event("engine.first_token", trace_id=TID, parent_id=eng, t=1.9)
    tv = _load_traceview()
    out = tv.summarize(rec.chrome_trace())
    (req,) = out["requests"]
    # TTFT belongs to the peer that actually served the generation...
    assert req["peer"] == "peer-b"
    # ...while the failover trail lists both peers it touched.
    assert req["peers"] == ["peer-a", "peer-b"]
    by_peer = out["aggregate"]["by_peer"]
    assert by_peer["peer-b"]["requests"] == 1
    assert by_peer["peer-b"]["failovers"] == 1
    assert by_peer["peer-b"]["ttft_p50_ms"] == pytest.approx(500, abs=1)


# ---------------------------------------------------------------------------
# cross-peer trace stitching (ISSUE 9, stitch_chrome_traces)
# ---------------------------------------------------------------------------

def _capture(build) -> dict:
    rec = TraceRecorder(enabled=True)
    build(rec)
    return rec.chrome_trace()


def test_stitch_assigns_lanes_and_dedupes_shared_journals():
    """Single-process fabrics share one recorder: the same records pulled
    via three journals must appear ONCE, with serve-track spans landing on
    the lane their peer attr names and engine spans inheriting their
    parent dispatch's lane."""
    from p2p_llm_tunnel_tpu.utils.tracing import stitch_chrome_traces

    rec = TraceRecorder(enabled=True)
    root = rec.add_span("proxy.request", trace_id=TID, t0=1.0, t1=3.0,
                        track="proxy", attrs={"status": 200})
    d = rec.add_span("serve.dispatch", trace_id=TID, parent_id=root,
                     track="serve", t0=1.1, t1=2.9,
                     attrs={"peer": "p1", "path": "/g"})
    rec.add_span("engine.request", trace_id=TID, parent_id=d,
                 t0=1.2, t1=2.8)
    shared = rec.chrome_trace()
    out = stitch_chrome_traces(
        {"proxy": shared, "p1": shared, "p2": shared})
    validate_chrome_trace(out)
    events = [e for e in out["traceEvents"] if e["ph"] != "M"]
    assert len(events) == 3  # deduped across the three identical pulls
    by_name = {e["name"]: e for e in events}
    # proxy-track events pin to the proxy lane even when pulled from a
    # peer journal; the dispatch and its engine child share p1's lane.
    assert by_name["proxy.request"]["pid"] != by_name["serve.dispatch"]["pid"]
    assert by_name["engine.request"]["pid"] == \
        by_name["serve.dispatch"]["pid"]
    assert out["stitch"]["sources"] == ["proxy", "p1", "p2"]
    assert out["stitch"]["stale"] == []
    assert out["stitch"]["partial_traces"] == []


def test_stitch_flags_evicted_journal_as_partial_not_crash():
    """A peer whose ring buffer evicted the sampled trace (or that died
    before its journal could be pulled) yields a PARTIAL chain: flagged in
    the stitch summary, never an exception (the federation-failure-mode
    satellite)."""
    from p2p_llm_tunnel_tpu.utils.tracing import stitch_chrome_traces

    def proxy_only(rec):
        rec.add_span("proxy.request", trace_id=TID, t0=1.0, t1=2.0,
                     track="proxy", attrs={"status": 200, "peer": "p1"})

    # Case 1: the serving peer's journal is empty (evicted) — the
    # proxy.request names p1 but no span of the trace sits on p1's lane.
    out = stitch_chrome_traces({
        "proxy": _capture(proxy_only),
        "p1": {"traceEvents": []},
    })
    validate_chrome_trace(out)
    assert out["stitch"]["partial_traces"] == [TID]
    assert out["stitch"]["stale"] == []

    # Case 2: the peer was unpullable entirely (dead/slow): stale AND the
    # chain is partial.
    out = stitch_chrome_traces({
        "proxy": _capture(proxy_only), "p1": None,
    })
    validate_chrome_trace(out)
    assert out["stitch"]["stale"] == ["p1"]
    assert out["stitch"]["partial_traces"] == [TID]

    # Case 3: an orphaned parent_id (the dispatch span evicted under the
    # engine span) is also partial — and still renders.
    def orphaned(rec):
        rec.add_span("engine.request", trace_id=TID,
                     parent_id="feedfeedfeed", t0=1.0, t1=2.0)

    out = stitch_chrome_traces({"proxy": _capture(orphaned)})
    validate_chrome_trace(out)
    assert out["stitch"]["partial_traces"] == [TID]


def test_stitch_keeps_colliding_cross_process_span_ids_distinct():
    """Counter-allocated span ids collide ACROSS processes: two peers'
    journals reusing span id 1 at different timestamps are different
    spans and must both survive the dedupe."""
    from p2p_llm_tunnel_tpu.utils.tracing import stitch_chrome_traces

    def peer_at(t0):
        def build(rec):
            rec.add_span("serve.dispatch", trace_id=TID, span_id="000001",
                         track="serve", t0=t0, t1=t0 + 1.0,
                         attrs={"peer": ""})
        return build

    # Distinct ts -> distinct records, each on its source journal's lane
    # (no peer attr, no parent: source fallback).
    out = stitch_chrome_traces({
        "p1": _capture(peer_at(1.0)), "p2": _capture(peer_at(5.0)),
    })
    events = [e for e in out["traceEvents"] if e["ph"] != "M"]
    assert len(events) == 2
    assert {e["pid"] for e in events} == {1, 2}


# ---------------------------------------------------------------------------
# engine-backed: herd chains + chaos topology (JAX; slow)
# ---------------------------------------------------------------------------

def _topology(records):
    """Per-trace span/event topology as a comparable value: the multiset
    of per-trace (name, parent-name) edge sets — trace and span IDS differ
    across runs, the STRUCTURE must not."""
    by_trace = {}
    for r in records:
        if r.trace_id is not None:
            by_trace.setdefault(r.trace_id, []).append(r)
    shapes = []
    for recs in by_trace.values():
        name_of = {r.span_id: r.name for r in recs}
        shapes.append(tuple(sorted(
            (r.name, name_of.get(r.parent_id)) for r in recs
        )))
    return tuple(sorted(shapes))


@pytest.mark.slow
def test_mux_herd_traces_chain_proxy_serve_engine():
    """ISSUE 6 acceptance: a 32-client mux herd emits, per request, one
    span chain crossing proxy -> serve -> engine under one propagated
    trace id, with the queue-wait + prefill-exec spans tiling the
    submit -> first-token window exactly; the capture validates against
    the trace-event schema."""
    from p2p_llm_tunnel_tpu.endpoints import http11
    from p2p_llm_tunnel_tpu.endpoints.proxy import run_proxy
    from p2p_llm_tunnel_tpu.engine.api import engine_backend
    from p2p_llm_tunnel_tpu.engine.engine import EngineConfig, InferenceEngine

    n = 32
    shared = "You are a helpful tunnel assistant; answer briefly. "

    async def main():
        engine = InferenceEngine(engine_cfg=EngineConfig(
            model="tiny", num_slots=8, max_seq=256, dtype="float32",
            mux=True, prefix_cache=True,
        ))
        await engine.start()
        serve_ch, proxy_ch = loopback_pair()
        serve_task = asyncio.create_task(
            run_serve(serve_ch, backend=engine_backend(engine, "tiny"))
        )
        ready: asyncio.Future = asyncio.get_running_loop().create_future()
        proxy_task = asyncio.create_task(
            run_proxy(proxy_ch, "127.0.0.1", 0, ready=ready)
        )
        port = await asyncio.wait_for(ready, 10.0)

        async def one(i):
            payload = json.dumps({
                "messages": [{"role": "user",
                              "content": f"{shared}q{i}"}],
                "max_tokens": 4, "stream": True,
            }).encode()
            resp = await http11.http_request(
                "POST", f"http://127.0.0.1:{port}/v1/chat/completions",
                {"content-type": "application/json"}, payload, timeout=120.0,
            )
            body = await resp.read_all()
            assert resp.status == 200
            assert body.strip().endswith(b"data: [DONE]")

        try:
            await asyncio.gather(*(one(i) for i in range(n)))
        finally:
            serve_task.cancel()
            proxy_task.cancel()
            serve_ch.close()
            await asyncio.gather(serve_task, proxy_task,
                                 return_exceptions=True)
            await engine.stop()

    with tracing_on(capacity=65536):
        asyncio.run(main())
        recs = global_tracer.records()
        by_trace = {}
        for r in recs:
            if r.trace_id is not None:
                by_trace.setdefault(r.trace_id, []).append(r)
        roots = [r for r in recs if r.name == "proxy.request"]
        assert len(roots) == n
        assert len(by_trace) == n  # one trace id per request, minted once
        for tid, trs in by_trace.items():
            spans = {r.name: r for r in trs if r.dur is not None}
            events = {r.name: r for r in trs if r.dur is None}
            for required in ("proxy.request", "proxy.frame_send",
                             "serve.dispatch", "engine.request",
                             "engine.queue_wait", "engine.prefill_exec"):
                assert required in spans, (tid, sorted(spans))
            for required in ("serve.frame_recv", "engine.first_token",
                             "engine.stream_end", "proxy.first_byte"):
                assert required in events, (tid, sorted(events))
            # The chain: serve.dispatch under proxy.request, engine.request
            # under serve.dispatch, the split under engine.request.
            assert (spans["serve.dispatch"].parent_id
                    == spans["proxy.request"].span_id)
            assert (spans["engine.request"].parent_id
                    == spans["serve.dispatch"].span_id)
            assert (spans["engine.queue_wait"].parent_id
                    == spans["engine.request"].span_id)
            # TTFT reconstruction: the two spans tile submit->first-token.
            qw, pf = spans["engine.queue_wait"], spans["engine.prefill_exec"]
            ft = events["engine.first_token"]
            assert qw.ts == pytest.approx(spans["engine.request"].ts,
                                          abs=1e-6)
            assert qw.ts + qw.dur == pytest.approx(pf.ts, abs=1e-6)
            assert pf.ts + pf.dur == pytest.approx(ft.ts, abs=1e-6)
            assert spans["engine.request"].attrs["finish"] in (
                "stop", "length"
            )
        # The shared template exercised the prefix-group machinery.
        assert any(r.name == "engine.prefix_own" for r in recs)
        # Engine-scope timeline rows recorded alongside.
        assert any(r.name == "engine.decode_burst" for r in recs)
        # And the export is schema-valid end to end.
        assert validate_chrome_trace(global_tracer.chrome_trace())


@pytest.mark.slow
def test_chaos_span_topology_deterministic():
    """Seeded drop/dup/stall on the client->serve path: two runs yield the
    SAME span topology — tracing is part of the `make chaos` determinism
    contract, not an exception to it."""
    from p2p_llm_tunnel_tpu.engine.api import engine_backend
    from p2p_llm_tunnel_tpu.engine.engine import EngineConfig, InferenceEngine
    from p2p_llm_tunnel_tpu.transport.chaos import ChaosChannel, ChaosSpec

    seed = int(os.environ.get("CHAOS_TEST_SEED", "5"))

    async def scenario():
        engine = InferenceEngine(engine_cfg=EngineConfig(
            model="tiny", num_slots=2, max_seq=256, dtype="float32",
            decode_steps=4, mux=True,
        ))
        await engine.start()
        serve_ch, client_ch = loopback_pair()
        chaos = ChaosChannel(client_ch, ChaosSpec.parse(
            f"seed={seed},drop=0.06,dup=0.05,stall=0.25:0.04"
        ))
        serve_task = asyncio.create_task(
            run_serve(serve_ch, backend=engine_backend(engine, "tiny"))
        )
        client = FrameClient(chaos, pad_pings=True, reply_pings=False)
        try:
            await client.handshake(timeout=30.0)
            results = []
            for i in range(4):
                r = await client.request(
                    "POST", "/v1/chat/completions",
                    body={"messages": [{"role": "user",
                                        "content": f"chaos {i}"}],
                          "stream": True, "max_tokens": 3,
                          "ignore_eos": True},
                    headers={TRACE_HEADER: f"{'%032x' % (i + 1)}/c{i}"},
                )
                results.append(r)
            for r in results:
                await client.wait(r, timeout=120.0)
            return tuple(chaos.faults)
        finally:
            client.close()
            serve_task.cancel()
            serve_ch.close()
            await asyncio.gather(serve_task, return_exceptions=True)
            await engine.stop()

    def run_once():
        with tracing_on(capacity=65536):
            faults = asyncio.run(scenario())
            return faults, _topology(global_tracer.records())

    f1, t1 = run_once()
    f2, t2 = run_once()
    assert f1 == f2, "fault schedule must be seed-deterministic"
    assert f1, "schedule fired no faults at these rates — spec broken"
    assert t1 == t2, "span topology must be identical across seeded runs"
    assert len(t1) == 4  # one topology per request trace
    for shape in t1:
        names = [name for name, _parent in shape]
        assert "serve.dispatch" in names and "engine.request" in names
