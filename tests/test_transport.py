"""Loopback transport contract tests (DataChannelPair semantics, rtc.rs:23-28)."""

import asyncio

import pytest

from p2p_llm_tunnel_tpu.transport import ChannelClosed, loopback_pair


def test_send_recv_roundtrip():
    async def run():
        a, b = loopback_pair()
        await a.send(b"hello")
        await b.send(b"world")
        assert await b.recv() == b"hello"
        assert await a.recv() == b"world"

    asyncio.run(run())


def test_order_preserved():
    async def run():
        a, b = loopback_pair()
        for i in range(100):
            await a.send(bytes([i]))
        got = [await b.recv() for _ in range(100)]
        assert got == [bytes([i]) for i in range(100)]

    asyncio.run(run())


def test_connected_immediately():
    async def run():
        a, b = loopback_pair()
        assert a.connected.is_set() and b.connected.is_set()
        assert not a.disconnected.is_set() and not b.disconnected.is_set()

    asyncio.run(run())


def test_close_propagates_to_peer():
    async def run():
        a, b = loopback_pair()
        a.close()
        assert a.disconnected.is_set()
        assert b.disconnected.is_set()
        with pytest.raises(ChannelClosed):
            await b.recv()
        with pytest.raises(ChannelClosed):
            await a.send(b"x")

    asyncio.run(run())


def test_close_drains_pending_messages_then_raises():
    async def run():
        a, b = loopback_pair()
        await a.send(b"one")
        await a.send(b"two")
        a.close()
        # Messages already delivered are still readable.
        assert await b.recv() == b"one"
        assert await b.recv() == b"two"
        with pytest.raises(ChannelClosed):
            await b.recv()

    asyncio.run(run())


def test_multiple_waiters_all_wake_on_close():
    async def run():
        a, b = loopback_pair()

        async def waiter():
            try:
                await b.recv()
                return "got"
            except ChannelClosed:
                return "closed"

        tasks = [asyncio.create_task(waiter()) for _ in range(4)]
        await asyncio.sleep(0.01)
        a.close()
        results = await asyncio.gather(*tasks)
        assert results == ["closed"] * 4

    asyncio.run(run())
