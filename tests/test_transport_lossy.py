"""Loss injection on the reliable UDP channel: the ARQ must deliver
everything, estimate RTT, and back its congestion window off under loss
instead of retransmit-storming.

The reference gets congestion control wholesale from SCTP inside the webrtc
crate (rtc.rs via Cargo.toml:14); these tests pin the behavior of the native
equivalent (transport/udp.py): Jacobson RTO, AIMD window, graceful
degradation at 1-5% loss (VERDICT r3 item 5).

Loss is injected by wrapping the asyncio datagram transport's ``sendto``
with a deterministic dropper — real sockets, real loopback, reproducible
loss pattern.
"""

import asyncio
import random
import time

import pytest

pytest.importorskip("cryptography")  # optional dep: skip (not fail) where absent

from p2p_llm_tunnel_tpu.transport.crypto import HandshakeKeys
from p2p_llm_tunnel_tpu.transport.arq import CWND_INIT
from p2p_llm_tunnel_tpu.transport.udp import WINDOW, UdpChannel


def run(coro):
    return asyncio.run(asyncio.wait_for(coro, 120))


class _LossyTransport:
    """Wraps an asyncio DatagramTransport; drops data-plane packets with
    probability ``p`` (deterministic seed).  Tiny packets (punch/ack sized)
    always pass so establishment and teardown stay reliable — loss on the
    bulk path is what the test targets."""

    def __init__(self, inner, p: float, seed: int = 7):
        self._inner = inner
        self._p = p
        self._rng = random.Random(seed)
        self.dropped = 0
        self.sent = 0

    def sendto(self, data, addr=None):
        self.sent += 1
        if len(data) > 200 and self._rng.random() < self._p:
            self.dropped += 1
            return
        self._inner.sendto(data, addr)

    def __getattr__(self, name):
        return getattr(self._inner, name)


async def _lossy_pair(p: float):
    a_keys, b_keys = HandshakeKeys(), HandshakeKeys()
    a = await UdpChannel.bind("127.0.0.1")
    b = await UdpChannel.bind("127.0.0.1")
    a.set_session(a_keys.derive(b_keys.public_bytes, True, "lossy"))
    b.set_session(b_keys.derive(a_keys.public_bytes, False, "lossy"))
    await asyncio.gather(
        a.punch([("127.0.0.1", b.local_port)]),
        b.punch([("127.0.0.1", a.local_port)]),
    )
    lossy = _LossyTransport(a._transport, p)
    a._transport = lossy
    return a, b, lossy


async def _pump(a: UdpChannel, b: UdpChannel, n_msgs: int, size: int) -> float:
    payloads = [bytes([i % 256]) * size for i in range(n_msgs)]
    t0 = time.monotonic()

    async def send_all():
        for m in payloads:
            await a.send(m)

    async def recv_all():
        for m in payloads:
            got = await asyncio.wait_for(b.recv(), 60)
            assert got == m, "payload corrupted or reordered"

    await asyncio.gather(send_all(), recv_all())
    return time.monotonic() - t0


@pytest.mark.parametrize("loss", [0.01, 0.05])
def test_lossy_delivery_complete_and_in_order(loss):
    async def main():
        a, b, lossy = await _lossy_pair(loss)
        try:
            await _pump(a, b, n_msgs=40, size=4000)  # 40 × 4 fragments
            stats = a.congestion_stats
            assert lossy.dropped > 0, "loss injection never fired"
            assert stats["retransmits"] > 0, "drops must trigger retransmits"
            assert stats["srtt"] is not None, "ACKs must produce RTT samples"
            assert stats["in_flight"] == 0, "everything must drain"
        finally:
            a.close()
            b.close()

    run(main())


def test_loss_triggers_multiplicative_backoff():
    async def main():
        a, b, lossy = await _lossy_pair(0.3)  # heavy loss forces timeouts
        try:
            await _pump(a, b, n_msgs=12, size=4000)
            stats = a.congestion_stats
            assert stats["retransmits"] > 0
            # ssthresh must have come down from the initial WINDOW cap:
            # proof that _on_timeout_loss ran (AIMD decrease happened).
            assert stats["ssthresh"] < WINDOW
        finally:
            a.close()
            b.close()

    run(main())


def test_clean_path_grows_window_and_tracks_rtt():
    async def main():
        a, b, lossy = await _lossy_pair(0.0)
        try:
            await _pump(a, b, n_msgs=60, size=4000)
            stats = a.congestion_stats
            assert stats["retransmits"] == 0, "no loss → no retransmits"
            assert stats["cwnd"] > CWND_INIT, "slow start must grow cwnd"
            # loopback RTT is sub-millisecond; the estimator must keep the
            # RTO clamped near its floor, not the old fixed 2 s ceiling.
            assert stats["srtt"] < 0.05
            assert stats["rto"] <= 0.2
        finally:
            a.close()
            b.close()

    run(main())


def test_throughput_degrades_sublinearly():
    """5% packet loss must not cost anywhere near a 2x slowdown once the
    estimator is warm (the r3 fixed-RTO design stalled a full 150 ms floor
    per loss).  Generous bound: < 5x, asserting shape not raw speed, so CI
    jitter can't flake it."""

    async def timed(loss):
        a, b, _ = await _lossy_pair(loss)
        try:
            # Warm the RTT estimator first so RTO reflects loopback.
            await _pump(a, b, n_msgs=20, size=1000)
            return await _pump(a, b, n_msgs=40, size=4000)
        finally:
            a.close()
            b.close()

    t_clean = run(timed(0.0))
    t_lossy = run(timed(0.05))
    assert t_lossy < max(5 * t_clean, t_clean + 2.0), (
        f"5% loss degraded throughput {t_lossy / t_clean:.1f}x "
        f"({t_clean:.2f}s → {t_lossy:.2f}s)"
    )
