"""Network transport tests: crypto, TCP channel, reliable UDP, full connect.

Real sockets over loopback stand in for WAN peers, mirroring how the
reference tests P2P with localhost processes (SURVEY.md §4).
"""

import asyncio

import pytest

pytest.importorskip("cryptography")  # optional dep: skip (not fail) where absent

from p2p_llm_tunnel_tpu.signaling import SignalServer
from p2p_llm_tunnel_tpu.transport import ChannelClosed, connect
from p2p_llm_tunnel_tpu.transport.crypto import CryptoError, HandshakeKeys, SecureBox
from p2p_llm_tunnel_tpu.transport.tcp import TcpChannel
from p2p_llm_tunnel_tpu.transport.udp import UdpChannel


def run(coro):
    return asyncio.run(asyncio.wait_for(coro, 60))


# -- crypto -----------------------------------------------------------------

def test_handshake_derives_matching_boxes():
    a, b = HandshakeKeys(), HandshakeKeys()
    box_a = a.derive(b.public_bytes, offerer=True, room="r")
    box_b = b.derive(a.public_bytes, offerer=False, room="r")
    wire = box_a.seal(b"hello tunnel")
    assert box_b.open(wire) == b"hello tunnel"
    back = box_b.seal(b"reply")
    assert box_a.open(back) == b"reply"


def test_tampered_ciphertext_rejected():
    a, b = HandshakeKeys(), HandshakeKeys()
    box_a = a.derive(b.public_bytes, True, "r")
    box_b = b.derive(a.public_bytes, False, "r")
    wire = bytearray(box_a.seal(b"payload"))
    wire[-1] ^= 0xFF
    with pytest.raises(CryptoError):
        box_b.open(bytes(wire))


def test_wrong_room_means_wrong_keys():
    a, b = HandshakeKeys(), HandshakeKeys()
    box_a = a.derive(b.public_bytes, True, "room-one")
    box_b = b.derive(a.public_bytes, False, "room-two")
    with pytest.raises(CryptoError):
        box_b.open(box_a.seal(b"x"))


# -- tcp channel ------------------------------------------------------------

async def _tcp_pair():
    a_keys, b_keys = HandshakeKeys(), HandshakeKeys()
    box_a = a_keys.derive(b_keys.public_bytes, True, "t")
    box_b = b_keys.derive(a_keys.public_bytes, False, "t")
    accepted = asyncio.Queue()

    async def on_conn(r, w):
        await accepted.put((r, w))

    server = await asyncio.start_server(on_conn, "127.0.0.1", 0)
    port = server.sockets[0].getsockname()[1]
    r_b, w_b = await asyncio.open_connection("127.0.0.1", port)
    r_a, w_a = await accepted.get()
    server.close()
    return TcpChannel(r_a, w_a, box_a), TcpChannel(r_b, w_b, box_b)


def test_tcp_roundtrip_and_boundaries():
    async def main():
        a, b = await _tcp_pair()
        await a.send(b"one")
        await a.send(b"two" * 10000)  # 30 KB frame
        await b.send(b"back")
        assert await b.recv() == b"one"
        assert await b.recv() == b"two" * 10000
        assert await a.recv() == b"back"
        a.close()
        b.close()

    run(main())


def test_tcp_close_propagates():
    async def main():
        a, b = await _tcp_pair()
        a.close()
        with pytest.raises(ChannelClosed):
            # b sees EOF and raises once drained
            for _ in range(10):
                await asyncio.wait_for(b.recv(), 5)
        assert b.disconnected.is_set()

    run(main())


# -- udp channel ------------------------------------------------------------

async def _udp_pair():
    a_keys, b_keys = HandshakeKeys(), HandshakeKeys()
    a = await UdpChannel.bind("127.0.0.1")
    b = await UdpChannel.bind("127.0.0.1")
    a.set_session(a_keys.derive(b_keys.public_bytes, True, "u"))
    b.set_session(b_keys.derive(a_keys.public_bytes, False, "u"))
    await asyncio.gather(
        a.punch([("127.0.0.1", b.local_port)]),
        b.punch([("127.0.0.1", a.local_port)]),
    )
    return a, b


def test_udp_roundtrip_order_and_fragmentation():
    async def main():
        a, b = await _udp_pair()
        msgs = [bytes([i]) * (i * 500) for i in range(1, 8)]  # up to 3.5 KB
        for m in msgs:
            await a.send(m)
        for m in msgs:
            assert await asyncio.wait_for(b.recv(), 10) == m
        # big frame: 64 KiB → 55 fragments, must reassemble exactly
        big = bytes(range(256)) * 256
        await b.send(big)
        assert await asyncio.wait_for(a.recv(), 10) == big
        a.close()
        b.close()

    run(main())


def test_udp_close_notifies_peer():
    async def main():
        a, b = await _udp_pair()
        a.close()
        await asyncio.wait_for(b.disconnected.wait(), 10)

    run(main())


def test_udp_idle_channel_stays_alive(monkeypatch):
    """Keepalives must keep an idle-but-healthy channel open past the
    dead-peer timeout (regression: keepalive was gated on last-HEARD and
    never elicited a reply, so idle tunnels died every DEAD_TIMEOUT)."""
    from p2p_llm_tunnel_tpu.transport import udp as udp_mod

    monkeypatch.setattr(udp_mod, "KEEPALIVE_INTERVAL", 0.2)
    monkeypatch.setattr(udp_mod, "DEAD_TIMEOUT", 1.0)

    async def main():
        a, b = await _udp_pair()
        await asyncio.sleep(3.0)  # 3x the dead timeout, fully idle
        assert not a.is_closed and not b.is_closed
        # still functional after the idle period
        await a.send(b"post-idle")
        assert await asyncio.wait_for(b.recv(), 10) == b"post-idle"
        a.close()
        b.close()

    run(main())


def test_udp_punch_timeout():
    async def main():
        keys = HandshakeKeys()
        peer = HandshakeKeys()
        ch = await UdpChannel.bind("127.0.0.1")
        ch.set_session(keys.derive(peer.public_bytes, True, "x"))
        with pytest.raises(TimeoutError):
            # port 1 on loopback: nothing answers
            await ch.punch([("127.0.0.1", 1)], timeout=1.0)

    run(main())


# -- full connect flow ------------------------------------------------------

@pytest.mark.parametrize("transport", ["udp", "tcp"])
def test_connect_end_to_end(transport):
    async def main():
        server = SignalServer(port=0)
        port = await server.start()
        url = f"ws://127.0.0.1:{port}"

        async def peer_a():
            ch, sig = await connect(url, "e2e-" + transport, transport)
            await ch.send(b"from-a")
            got = await asyncio.wait_for(ch.recv(), 10)
            await sig.close()
            ch.close()
            return got

        async def peer_b():
            await asyncio.sleep(0.2)  # let A join first → A is offerer
            ch, sig = await connect(url, "e2e-" + transport, transport)
            got = await asyncio.wait_for(ch.recv(), 10)
            await ch.send(b"from-b")
            await asyncio.sleep(0.5)  # let the frame flush before close
            await sig.close()
            ch.close()
            return got

        got_a, got_b = await asyncio.gather(peer_a(), peer_b())
        assert got_a == b"from-b"
        assert got_b == b"from-a"
        await server.stop()

    run(main())
