"""tunnelcheck rule suite: positive + negative fixtures per rule, waiver
parsing, and the self-run invariant that the shipped tree stays clean.

Fast and jax-free: the checker is pure ``ast``, so these tests are plain
tier-1 members with no accelerator or optional-dep requirements.
"""

from __future__ import annotations

import textwrap
from pathlib import Path

import pytest

from tools.tunnelcheck import run_paths
from tools.tunnelcheck.__main__ import main as tunnelcheck_main

REPO_ROOT = Path(__file__).resolve().parents[1]


def check(tmp_path: Path, code: str, filename: str = "snippet.py", rules=None):
    """Write one fixture file and return (active, waived) violations."""
    f = tmp_path / filename
    f.parent.mkdir(parents=True, exist_ok=True)
    f.write_text(textwrap.dedent(code))
    return run_paths([f], rules=rules)


def rules_of(violations):
    return [v.rule for v in violations]


# ---------------------------------------------------------------------------
# TC00 — parse errors are findings, not crashes
# ---------------------------------------------------------------------------


def test_tc00_syntax_error_is_reported(tmp_path):
    active, _ = check(tmp_path, "def broken(:\n")
    assert rules_of(active) == ["TC00"]


# ---------------------------------------------------------------------------
# TC01 — blocking calls inside async def
# ---------------------------------------------------------------------------


def test_tc01_flags_time_sleep_in_async(tmp_path):
    active, _ = check(
        tmp_path,
        """
        import time

        async def handler():
            time.sleep(0.1)
        """,
    )
    assert rules_of(active) == ["TC01"]
    assert "asyncio.sleep" in active[0].message


def test_tc01_resolves_from_import_alias(tmp_path):
    active, _ = check(
        tmp_path,
        """
        from time import sleep
        import subprocess as sp

        async def handler():
            sleep(1)
            sp.check_output(["ls"])
        """,
    )
    assert rules_of(active) == ["TC01", "TC01"]


def test_tc01_local_import_does_not_pollute_module_scope(tmp_path):
    # A sync helper's local `from time import sleep` must not make the
    # async function's asyncio `sleep` resolve to time.sleep...
    active, _ = check(
        tmp_path,
        """
        from asyncio import sleep

        def helper():
            from time import sleep
            sleep(1)

        async def handler():
            await sleep(0.1)
        """,
    )
    assert active == []


def test_tc01_local_import_inside_async_def_still_resolves(tmp_path):
    # ...while a local import inside the async def itself still counts.
    active, _ = check(
        tmp_path,
        """
        async def handler():
            from time import sleep
            sleep(1)
        """,
    )
    assert rules_of(active) == ["TC01"]


def test_tc01_rebound_import_resolves_to_last_binding(tmp_path):
    # Python binding semantics: the LAST import of a rebound name wins.
    active, _ = check(
        tmp_path,
        """
        from time import sleep
        from asyncio import sleep

        async def handler():
            await sleep(0.1)
        """,
    )
    assert active == []
    active, _ = check(
        tmp_path,
        """
        from asyncio import sleep
        from time import sleep

        async def handler():
            sleep(0.1)
        """,
    )
    assert rules_of(active) == ["TC01"]


def test_tc01_flags_blocking_file_io(tmp_path):
    active, _ = check(
        tmp_path,
        """
        async def handler(path):
            with open(path) as f:
                return f.read()
        """,
    )
    assert rules_of(active) == ["TC01"]


def test_tc01_allows_sync_and_awaited_equivalents(tmp_path):
    active, _ = check(
        tmp_path,
        """
        import asyncio
        import time

        def sync_helper():
            time.sleep(0.1)  # fine: not on the event loop

        async def handler():
            await asyncio.sleep(0.1)

            def executor_job():
                time.sleep(1)  # fine: nearest enclosing function is sync

            return executor_job
        """,
    )
    assert active == []


# ---------------------------------------------------------------------------
# TC02 — jit signature drift
# ---------------------------------------------------------------------------


def test_tc02_static_argnums_out_of_range(tmp_path):
    active, _ = check(
        tmp_path,
        """
        import jax

        def step(params, tokens, steps):
            return tokens

        fn = jax.jit(step, static_argnums=(2, 7))
        """,
    )
    assert rules_of(active) == ["TC02"]
    assert "index 7" in active[0].message


def test_tc02_static_argnames_unknown_name(tmp_path):
    active, _ = check(
        tmp_path,
        """
        import jax

        def step(params, tokens, steps):
            return tokens

        fn = jax.jit(step, static_argnames=("step_count",))
        """,
    )
    assert rules_of(active) == ["TC02"]
    assert "step_count" in active[0].message


def test_tc02_direct_call_arity(tmp_path):
    active, _ = check(
        tmp_path,
        """
        import jax

        def step(params, tokens, steps):
            return tokens

        out = jax.jit(step, static_argnums=(2,))(p, t)
        """,
    )
    assert rules_of(active) == ["TC02"]
    assert "missing: steps" in active[0].message


def test_tc02_keyword_fun_spelling_is_checked(tmp_path):
    active, _ = check(
        tmp_path,
        """
        import jax

        def step(params, tokens):
            return tokens

        fn = jax.jit(fun=step, static_argnums=(5,))
        """,
    )
    assert rules_of(active) == ["TC02"]


def test_tc02_partial_decorator_checked(tmp_path):
    active, _ = check(
        tmp_path,
        """
        import functools
        import jax

        @functools.partial(jax.jit, static_argnums=(3,), donate_argnums=(0,))
        def step(params, tokens):
            return tokens
        """,
    )
    assert rules_of(active) == ["TC02"]


def test_tc02_regression_old_perf_probe_shape(tmp_path):
    """The PR 2 incident, verbatim in shape: ``_decode_fn`` grew a ``bias``
    parameter (13 total), but the probe still jitted it with the stale
    ``static_argnums=(10, 11)`` and lowered with the old 12-argument call.
    The indices are in range — only the arity check catches it, exactly the
    class of drift tests never see because scripts/ is never imported."""
    active, _ = check(
        tmp_path,
        """
        import jax

        class Engine:
            def _decode_fn(self, params, kv_cache, tokens, positions, counts,
                           bias, ov_mask, ov_tok, ov_pos, samp, key, kv_view,
                           steps):
                return tokens

        def probe(eng, params, kv_cache, tokens, positions, counts, ovm, ovt,
                  ovp, samp, key, kv_view, steps):
            return jax.jit(eng._decode_fn, static_argnums=(10, 11)).lower(
                params, kv_cache, tokens, positions, counts, ovm, ovt,
                ovp, samp, key, kv_view, steps,
            )
        """,
    )
    assert rules_of(active) == ["TC02"]
    assert "missing" in active[0].message


def test_tc02_clean_on_valid_shapes(tmp_path):
    active, _ = check(
        tmp_path,
        """
        import jax

        class Engine:
            def _decode_fn(self, params, tokens, steps):
                return tokens

        def probe(eng, params, tokens, steps):
            return jax.jit(eng._decode_fn, static_argnums=(2,)).lower(
                params, tokens, steps
            )

        variadic = jax.jit(lambda *a: a, static_argnums=(5,))
        unresolvable = jax.jit(some_imported_fn, static_argnums=(99,))
        """,
    )
    assert active == []


# ---------------------------------------------------------------------------
# TC03 — host sync inside traced functions
# ---------------------------------------------------------------------------


def test_tc03_item_in_jitted_function(tmp_path):
    active, _ = check(
        tmp_path,
        """
        import jax

        def step(carry, x):
            n = carry.item()
            return carry, x

        fn = jax.jit(step)
        """,
    )
    assert rules_of(active) == ["TC03"]
    assert ".item()" in active[0].message


def test_tc03_scan_body_and_np_asarray(tmp_path):
    active, _ = check(
        tmp_path,
        """
        import numpy as np
        from jax import lax

        def body(carry, x):
            host = np.asarray(x)
            return carry, host

        ys = lax.scan(body, 0, xs)
        """,
    )
    assert rules_of(active) == ["TC03"]
    assert "numpy.asarray" in active[0].message


def test_tc03_python_if_on_traced_comparison(tmp_path):
    active, _ = check(
        tmp_path,
        """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def step(x):
            if jnp.max(x) > 0:
                return x
            return -x
        """,
    )
    assert rules_of(active) == ["TC03"]
    assert "lax.cond" in active[0].message


def test_tc03_float_of_jax_expression(tmp_path):
    active, _ = check(
        tmp_path,
        """
        import jax
        import jax.numpy as jnp

        def step(x):
            return float(jnp.sum(x))

        fn = jax.jit(step)
        """,
    )
    assert rules_of(active) == ["TC03"]


def test_tc03_static_shape_and_dtype_branches_are_legal(tmp_path):
    # shape/ndim/dtype are plain Python values under trace; branching on
    # them is legal and must not be pushed toward lax.cond.
    active, _ = check(
        tmp_path,
        """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def step(x):
            if jnp.ndim(x) == 2:
                return x
            if x.shape[0] > 1 and x.dtype == jnp.int8:
                return x
            n = int(jnp.shape(x)[0])
            return -x
        """,
    )
    assert active == []


def test_tc03_traced_parameter_concretisation(tmp_path):
    # float()/if on a traced *parameter* must be caught even with no
    # jnp call in the expression.
    active, _ = check(
        tmp_path,
        """
        import jax

        def step(x, steps):
            if x > 0:
                return float(x)
            return 0.0

        fn = jax.jit(step, static_argnums=(1,))
        """,
    )
    assert rules_of(active) == ["TC03", "TC03"]


def test_tc03_static_argnums_params_are_exempt(tmp_path):
    # Params marked static at the jit site are Python values: branching
    # and float() on them is legal, as is `is None` on traced args.
    active, _ = check(
        tmp_path,
        """
        import jax

        def step(x, mask, steps):
            if steps > 4:
                return x * float(steps)
            if mask is not None:
                return x + mask
            return x

        fn = jax.jit(step, static_argnums=(2,))
        """,
    )
    assert active == []


def test_tc03_scan_carry_name_collision_not_traced(tmp_path):
    # Only the function positions of scan/fori/while are traced; a carry
    # arg sharing its name with a host-side def must not drag it in.
    active, _ = check(
        tmp_path,
        """
        import numpy as np
        from jax import lax

        def helper(x):
            return float(np.asarray(x))

        def body(carry, x):
            return carry, x

        ys = lax.scan(body, helper, xs)
        out = lax.fori_loop(lower, helper, body, init)
        """,
    )
    assert active == []


def test_tc03_untraced_functions_are_free(tmp_path):
    active, _ = check(
        tmp_path,
        """
        import numpy as np

        def host_side(x):
            return float(np.asarray(x).item())

        def static_config(x, use_bias):
            if use_bias:  # static python control flow is fine under trace
                return x
            return -x

        import jax
        fn = jax.jit(static_config, static_argnums=(1,))
        """,
    )
    assert active == []


# ---------------------------------------------------------------------------
# TC04 — optional-dep hygiene
# ---------------------------------------------------------------------------


def test_tc04_module_level_optional_import(tmp_path):
    active, _ = check(
        tmp_path,
        """
        import websockets
        """,
    )
    assert rules_of(active) == ["TC04"]


def test_tc04_gating_try_except_is_still_module_level(tmp_path):
    # Only the three wrapper modules may gate; anyone else must import them.
    active, _ = check(
        tmp_path,
        """
        try:
            from cryptography.hazmat.primitives import hashes
        except ImportError:
            hashes = None
        """,
    )
    assert rules_of(active) == ["TC04"]


def test_tc04_type_checking_block_is_exempt(tmp_path):
    # `if TYPE_CHECKING:` never executes, so a type-only import cannot
    # cause the PR 1 collection-error incident.
    active, _ = check(
        tmp_path,
        """
        from typing import TYPE_CHECKING

        if TYPE_CHECKING:
            import websockets
        """,
    )
    assert active == []


def test_tc04_function_local_import_ok(tmp_path):
    active, _ = check(
        tmp_path,
        """
        def connect():
            import websockets
            return websockets
        """,
    )
    assert active == []


def test_tc04_gated_wrappers_are_exempt(tmp_path):
    active, _ = check(
        tmp_path,
        """
        try:
            import websockets
        except ImportError:
            websockets = None
        """,
        filename="p2p_llm_tunnel_tpu/signaling/client.py",
    )
    assert active == []


# ---------------------------------------------------------------------------
# TC05 — MessageType dispatch exhaustiveness + error-code registry
# ---------------------------------------------------------------------------

DISPATCH_PREAMBLE = """
from p2p_llm_tunnel_tpu.protocol.frames import MessageType, TunnelMessage

def dispatch(msg):
"""


def test_tc05_dispatch_without_default(tmp_path):
    active, _ = check(
        tmp_path,
        DISPATCH_PREAMBLE
        + """
    if msg.msg_type == MessageType.RES_BODY:
        return "body"
    elif msg.msg_type == MessageType.RES_END:
        return "end"
        """,
    )
    assert rules_of(active) == ["TC05"]
    assert "unhandled" in active[0].message


def test_tc05_dispatch_with_default_is_clean(tmp_path):
    active, _ = check(
        tmp_path,
        DISPATCH_PREAMBLE
        + """
    if msg.msg_type == MessageType.RES_BODY:
        return "body"
    elif msg.msg_type == MessageType.RES_END:
        return "end"
    else:
        return "ignored"
        """,
    )
    assert active == []


def test_tc05_else_containing_an_if_is_a_default(tmp_path):
    # An `else:` whose body starts with an `if` must not be mistaken for
    # another elif link — it IS the explicit default.
    active, _ = check(
        tmp_path,
        DISPATCH_PREAMBLE
        + """
    if msg.msg_type == MessageType.RES_BODY:
        return "body"
    elif msg.msg_type == MessageType.RES_END:
        return "end"
    else:
        if msg.stream_id == 0:
            return "control"
        return "ignored"
        """,
    )
    assert active == []


def test_tc05_covers_kv_pages_frame_family(tmp_path):
    """ISSUE 20: a dispatch ladder over the new KV_PAGES_* transfer
    members is a MessageType dispatch like any other — no default arm,
    TC05 fires.  Pins that enum growth grows the rule's coverage for
    free (the exhaustiveness check reads the enum, not a hand list)."""
    active, _ = check(
        tmp_path,
        DISPATCH_PREAMBLE
        + """
    if msg.msg_type == MessageType.KV_PAGES_HDR:
        return "hdr"
    elif msg.msg_type == MessageType.KV_PAGES_CHUNK:
        return "chunk"
    elif msg.msg_type == MessageType.KV_PAGES_END:
        return "end"
        """,
    )
    assert rules_of(active) == ["TC05"]
    assert "unhandled" in active[0].message


def test_tc05_sees_through_import_aliases(tmp_path):
    active, _ = check(
        tmp_path,
        """
        from p2p_llm_tunnel_tpu.protocol.frames import MessageType as MT

        def dispatch(msg):
            if msg.msg_type == MT.RES_BODY:
                return "body"
            elif msg.msg_type == MT.RES_END:
                return "end"
        """,
    )
    assert rules_of(active) == ["TC05"]


def test_tc05_different_subjects_are_not_one_dispatch(tmp_path):
    # Comparing two DIFFERENT expressions against members is not a
    # dispatch over one frame's type.
    active, _ = check(
        tmp_path,
        DISPATCH_PREAMBLE
        + """
    if msg.first.msg_type == MessageType.RES_BODY:
        return "a"
    elif msg.second.msg_type == MessageType.RES_END:
        return "b"
        """,
    )
    assert active == []


def test_tc05_single_guard_is_not_a_dispatch(tmp_path):
    active, _ = check(
        tmp_path,
        DISPATCH_PREAMBLE
        + """
    if msg.msg_type != MessageType.HELLO:
        raise RuntimeError("expected HELLO")
        """,
    )
    assert active == []


def test_tc05_unregistered_typed_error_code(tmp_path):
    active, _ = check(
        tmp_path,
        """
        from p2p_llm_tunnel_tpu.protocol.frames import TunnelMessage

        frame = TunnelMessage.typed_error(1, "overloadedd", "shed")
        """,
    )
    assert rules_of(active) == ["TC05"]
    assert "overloadedd" in active[0].message


def test_tc05_registered_code_and_tunnel_code_clean(tmp_path):
    active, _ = check(
        tmp_path,
        """
        from p2p_llm_tunnel_tpu.protocol.frames import TunnelMessage

        class DeadlineExceeded(Exception):
            tunnel_code = "timeout"

        frame = TunnelMessage.typed_error(1, "busy", "shed")
        """,
    )
    assert active == []


def test_tc05_unregistered_tunnel_code(tmp_path):
    active, _ = check(
        tmp_path,
        """
        class Oops(Exception):
            tunnel_code = "exploded"
        """,
    )
    assert rules_of(active) == ["TC05"]


def test_tc05_annotated_tunnel_code_and_keyword_code(tmp_path):
    # The typed variants must not slip past the registry check.
    active, _ = check(
        tmp_path,
        """
        from p2p_llm_tunnel_tpu.protocol.frames import TunnelMessage

        class Oops(Exception):
            tunnel_code: str = "exploded"

        frame = TunnelMessage.typed_error(1, code="overloadedd", msg="x")
        """,
    )
    assert rules_of(active) == ["TC05", "TC05"]


# ---------------------------------------------------------------------------
# TC06 — metrics-name registry
# ---------------------------------------------------------------------------


def test_tc06_typod_write_is_flagged(tmp_path):
    active, _ = check(
        tmp_path,
        """
        from p2p_llm_tunnel_tpu.utils.metrics import global_metrics

        global_metrics.inc("engine_tokens_totl")
        """,
    )
    assert rules_of(active) == ["TC06"]
    assert "engine_tokens_totl" in active[0].message


def test_tc06_typod_read_is_flagged(tmp_path):
    # /healthz-style reads are held to the catalogue too.
    active, _ = check(
        tmp_path,
        """
        from p2p_llm_tunnel_tpu.utils.metrics import global_metrics

        depth = global_metrics.gauge("engine_queue_dept")
        """,
    )
    assert rules_of(active) == ["TC06"]


def test_tc06_catalogued_names_are_clean(tmp_path):
    active, _ = check(
        tmp_path,
        """
        from p2p_llm_tunnel_tpu.utils.metrics import global_metrics

        global_metrics.inc("engine_tokens_total")
        global_metrics.set_gauge("engine_queue_depth", 3)
        global_metrics.observe("engine_ttft_ms", 12.5)
        depth = global_metrics.gauge("engine_queue_depth")
        dynamic = "engine_" + "tokens_total"
        global_metrics.inc(dynamic)  # non-literal names are out of scope
        """,
    )
    assert active == []


# ---------------------------------------------------------------------------
# Waivers
# ---------------------------------------------------------------------------


def test_line_waiver_suppresses_and_is_reported_as_waived(tmp_path):
    active, waived = check(
        tmp_path,
        """
        import time

        async def handler():
            time.sleep(0.01)  # tunnelcheck: disable=TC01  startup-only path
        """,
    )
    assert active == []
    assert rules_of(waived) == ["TC01"]


def test_line_waiver_is_rule_specific(tmp_path):
    active, _ = check(
        tmp_path,
        """
        import time

        async def handler():
            time.sleep(0.01)  # tunnelcheck: disable=TC02
        """,
    )
    assert rules_of(active) == ["TC01"]


def test_waiver_inside_a_string_literal_is_inert(tmp_path):
    # Only real comment tokens waive — a fixture string that *contains*
    # waiver syntax (like this test file itself) must not gag the checker.
    active, _ = check(
        tmp_path,
        '''
        import time

        FIXTURE = """
        # tunnelcheck: disable-file=TC01
        x = 1  # tunnelcheck: disable=all
        """

        async def handler():
            time.sleep(1)
        ''',
    )
    assert rules_of(active) == ["TC01"]


def test_waiver_on_a_continuation_line_suppresses(tmp_path):
    # The natural placement — next to the offending argument of a
    # multi-line call — must work, not just the statement's first line.
    active, waived = check(
        tmp_path,
        """
        from p2p_llm_tunnel_tpu.utils.metrics import global_metrics

        global_metrics.observe(
            "bench_only_series",  # tunnelcheck: disable=TC06  ad-hoc probe
            1.0,
        )
        """,
    )
    assert active == []
    assert rules_of(waived) == ["TC06"]


def test_file_waiver_and_disable_all(tmp_path):
    active, waived = check(
        tmp_path,
        """
        # tunnelcheck: disable-file=TC01
        import time
        import subprocess

        async def a():
            time.sleep(1)

        async def b():
            subprocess.run(["ls"])  # tunnelcheck: disable=all
        """,
    )
    assert active == []
    assert len(waived) == 2


# ---------------------------------------------------------------------------
# Self-run + CLI
# ---------------------------------------------------------------------------


def test_self_run_shipped_tree_is_clean():
    """The repo must always pass its own checker (the `make lint` gate) —
    including the repo-root entry points bench.py and __graft_entry__.py,
    which read catalogued metrics and jit model functions respectively."""
    active, _ = run_paths(
        [
            REPO_ROOT / "p2p_llm_tunnel_tpu",
            REPO_ROOT / "scripts",
            REPO_ROOT / "tests",
            REPO_ROOT / "bench.py",
            REPO_ROOT / "__graft_entry__.py",
        ]
    )
    assert active == [], "\n".join(v.render(REPO_ROOT) for v in active)


def test_overlapping_paths_scan_each_file_once(tmp_path):
    f = tmp_path / "bad.py"
    f.write_text("import time\n\nasync def f():\n    time.sleep(1)\n")
    active, _ = run_paths([tmp_path, f])
    assert rules_of(active) == ["TC01"]


def test_cli_exit_codes(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text("import time\n\nasync def f():\n    time.sleep(1)\n")
    good = tmp_path / "good.py"
    good.write_text("x = 1\n")

    assert tunnelcheck_main([str(good)]) == 0
    assert tunnelcheck_main([str(bad)]) == 1
    out = capsys.readouterr().out
    assert "TC01" in out
    assert tunnelcheck_main([]) == 2
    assert tunnelcheck_main([str(tmp_path / "missing.py")]) == 2
    assert tunnelcheck_main(["--list-rules"]) == 0
    assert "TC06" in capsys.readouterr().out


def test_cli_rule_filter(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("import time\n\nasync def f():\n    time.sleep(1)\n")
    assert tunnelcheck_main([str(bad), "--rules", "TC02"]) == 0
    assert tunnelcheck_main([str(bad), "--rules", "TC01"]) == 1
    assert tunnelcheck_main([str(bad), "--rules", "TC99"]) == 2
    # TC00 appears in --list-rules, so the filter accepts it (parse errors
    # are unfilterable and reported regardless of --rules).
    assert tunnelcheck_main([str(bad), "--rules", "TC00"]) == 0
    unparseable = tmp_path / "unparseable.py"
    unparseable.write_text("def broken(:\n")
    assert tunnelcheck_main([str(unparseable), "--rules", "TC06"]) == 1


def test_run_paths_rejects_unknown_rule_ids(tmp_path):
    f = tmp_path / "x.py"
    f.write_text("x = 1\n")
    with pytest.raises(ValueError, match="TC1"):
        run_paths([f], rules=["TC1"])
    # TC00 is accepted (always-on, unfilterable).
    active, _ = run_paths([f], rules=["TC00"])
    assert active == []


def test_registries_match_runtime():
    """The statically-parsed registries agree with the live modules, so the
    checker can't drift from what the code actually enforces."""
    from p2p_llm_tunnel_tpu.protocol.frames import ERROR_CODES, MessageType
    from p2p_llm_tunnel_tpu.utils.metrics import METRICS_CATALOG
    from tools.tunnelcheck.core import ProjectContext

    ctx = ProjectContext([])
    assert set(ctx.message_types) == {m.name for m in MessageType}
    assert ctx.error_codes == set(ERROR_CODES)
    assert ctx.metrics_names == set(METRICS_CATALOG)


# ---------------------------------------------------------------------------
# TC07 — device dispatches inside per-request/slot loops (serving path)
# ---------------------------------------------------------------------------

ENGINE_FIXTURE = "p2p_llm_tunnel_tpu/engine/fixture_engine.py"


def test_tc07_flags_jit_call_in_request_loop(tmp_path):
    active, _ = check(
        tmp_path,
        """
        import jax

        class Engine:
            def __init__(self):
                self._jit_copy = jax.jit(lambda x: x)

            def admit(self, runs):
                for run in runs:
                    self._jit_copy(run)
        """,
        filename=ENGINE_FIXTURE,
        rules=["TC07"],
    )
    assert rules_of(active) == ["TC07"]
    assert "_jit_copy" in active[0].message


def test_tc07_flags_device_get_in_request_loop(tmp_path):
    active, _ = check(
        tmp_path,
        """
        import jax

        def drain(requests):
            out = []
            for r in requests:
                out.append(jax.device_get(r))
            return out
        """,
        filename=ENGINE_FIXTURE,
        rules=["TC07"],
    )
    assert rules_of(active) == ["TC07"]


def test_tc07_flags_factory_returned_callable_per_slot(tmp_path):
    """The exact r5 class: a helper factory returns jitted copy ops
    (tuple-unpacked), and one of them is dispatched once per matched
    request inside the admission loop."""
    active, _ = check(
        tmp_path,
        """
        import jax

        def make_copy_ops():
            return jax.jit(lambda c: c), jax.jit(lambda c: c)

        class Engine:
            def __init__(self):
                self._copy_in, self._copy_out = make_copy_ops()

            def admit(self, hits):
                for slot, blocks in hits:
                    self.cache = self._copy_in(self.cache)
        """,
        filename=ENGINE_FIXTURE,
        rules=["TC07"],
    )
    assert rules_of(active) == ["TC07"]
    assert "_copy_in" in active[0].message


def test_tc07_flags_dispatching_helper_via_executor(tmp_path):
    """A method that transitively dispatches, handed to run_in_executor
    once per request, is still one dispatch per iteration."""
    active, _ = check(
        tmp_path,
        """
        import jax

        class Engine:
            def __init__(self):
                self._jit_prefill = jax.jit(lambda t: t)

            def _dispatch_one(self, tokens):
                return self._jit_prefill(tokens)

            async def admit(self, loop, admitted):
                for run in admitted:
                    await loop.run_in_executor(None, self._dispatch_one, run)
        """,
        filename=ENGINE_FIXTURE,
        rules=["TC07"],
    )
    assert rules_of(active) == ["TC07"]


def test_tc07_batched_outside_loop_and_warmup_loops_clean(tmp_path):
    """The fixed shape (pack the wave, ONE dispatch after the loop) and
    compile-time loops over view buckets are clean; so is the engine's
    `while self._running` main loop (word-wise subject matching — one
    dispatch per BURST is the design)."""
    active, _ = check(
        tmp_path,
        """
        import jax

        class Engine:
            def __init__(self):
                self._jit_prefill = jax.jit(lambda t: t)
                self._running = True

            def admit(self, runs):
                batch = [r.tokens for r in runs]
                return self._jit_prefill(batch)

            def warmup(self, views):
                for view in views:
                    self._jit_prefill(view)

            def loop(self):
                while self._running:
                    self._jit_prefill(0)
        """,
        filename=ENGINE_FIXTURE,
        rules=["TC07"],
    )
    assert active == []


def test_tc07_out_of_scope_modules_not_scanned(tmp_path):
    """The rule covers the engine/endpoints serving path only — model
    code legitimately maps jitted fns over layer lists."""
    active, _ = check(
        tmp_path,
        """
        import jax

        def apply(layers):
            f = jax.jit(lambda x: x)
            for layer in layers:  # 'layer' is not a request subject anyway
                f(layer)

        def per_prompt(prompts):
            g = jax.jit(lambda x: x)
            for p in prompts:
                g(p)
        """,
        filename="p2p_llm_tunnel_tpu/models/fixture_model.py",
        rules=["TC07"],
    )
    assert active == []


def test_tc07_waiver_records_granularity_contract(tmp_path):
    active, waived = check(
        tmp_path,
        """
        import jax

        class Engine:
            def __init__(self):
                self._jit_copy = jax.jit(lambda x: x)

            def admit(self, hits):
                for lo in range(0, len(hits), 8):
                    self._jit_copy(hits[lo:lo + 8])  # tunnelcheck: disable=TC07  one dispatch per 8-wide sub-batch
        """,
        filename=ENGINE_FIXTURE,
        rules=["TC07"],
    )
    assert active == []
    assert rules_of(waived) == ["TC07"]


# ---------------------------------------------------------------------------
# TC08 — EngineConfig fields must be wired to cli.py flags (config rot)
# ---------------------------------------------------------------------------


def _tc08_tree(tmp_path, engine_src, cli_src):
    (tmp_path / "pkg").mkdir(exist_ok=True)
    eng = tmp_path / "pkg" / "engine.py"
    eng.write_text(textwrap.dedent(engine_src))
    cli = tmp_path / "pkg" / "cli.py"
    cli.write_text(textwrap.dedent(cli_src))
    return run_paths([eng, cli], rules=["TC08"])


def test_tc08_unwired_field_is_flagged(tmp_path):
    active, _ = _tc08_tree(
        tmp_path,
        """
        from dataclasses import dataclass

        @dataclass
        class EngineConfig:
            model: str = "tiny"
            zz_orphan_knob: int = 0
        """,
        """
        from pkg.engine import EngineConfig

        def make(args):
            return EngineConfig(model=args.model)
        """,
    )
    assert rules_of(active) == ["TC08"]
    assert "zz_orphan_knob" in active[0].message


def test_tc08_regression_env_only_serving_levers(tmp_path):
    """The incident class this rule exists for: decode_steps_eager and
    prefill_rows were REAL serving levers (benched via BENCH_* env knobs,
    documented in README) that no serve flag could reach for four PRs —
    operators of the deployed binary simply could not turn the TTFT lever.
    The fixture mirrors that exact shape."""
    active, _ = _tc08_tree(
        tmp_path,
        """
        from dataclasses import dataclass

        @dataclass
        class EngineConfig:
            model: str = "tiny"
            decode_steps: int = 8
            decode_steps_eager: int = 4
            prefill_rows: int = 8
        """,
        """
        from pkg.engine import EngineConfig

        def make(args):
            return EngineConfig(
                model=args.model, decode_steps=args.decode_steps,
            )
        """,
    )
    assert sorted(v.message.split()[0] for v in active) == [
        "EngineConfig.decode_steps_eager",
        "EngineConfig.prefill_rows",
    ]


def test_tc08_wired_fields_are_clean(tmp_path):
    active, _ = _tc08_tree(
        tmp_path,
        """
        from dataclasses import dataclass

        @dataclass
        class EngineConfig:
            model: str = "tiny"
            slots: int = 8
        """,
        """
        from pkg.engine import EngineConfig

        def make(args):
            return EngineConfig(model=args.model, slots=args.slots)
        """,
    )
    assert active == []


def test_tc08_waiver_names_the_reason(tmp_path):
    active, waived = _tc08_tree(
        tmp_path,
        """
        from dataclasses import dataclass

        @dataclass
        class EngineConfig:
            model: str = "tiny"
            bucket: int = 16  # tunnelcheck: disable=TC08  geometry pin, programmatic only
        """,
        """
        from pkg.engine import EngineConfig

        def make(args):
            return EngineConfig(model=args.model)
        """,
    )
    assert active == []
    assert rules_of(waived) == ["TC08"]


def test_tc08_fixture_without_cli_checks_against_repo_cli(tmp_path):
    """Scanning an EngineConfig definition WITHOUT a cli.py in the scan
    set falls back to the repo's real CLI — so `tunnelcheck engine.py`
    alone still catches rot, and a bogus field is flagged against it."""
    f = tmp_path / "engine.py"
    f.write_text(textwrap.dedent(
        """
        from dataclasses import dataclass

        @dataclass
        class EngineConfig:
            model: str = "tiny"
            zz_never_a_real_flag: int = 0
        """
    ))
    active, _ = run_paths([f], rules=["TC08"])
    assert rules_of(active) == ["TC08"]
    assert "zz_never_a_real_flag" in active[0].message


# ---------------------------------------------------------------------------
# TC09 — span-name registry + host-only emission (ISSUE 6)
# ---------------------------------------------------------------------------


def test_tc09_unknown_span_name_is_flagged(tmp_path):
    active, _ = check(
        tmp_path,
        """
        from p2p_llm_tunnel_tpu.utils.tracing import global_tracer

        def emit(tid):
            global_tracer.add_span("engine.queue_wiat", trace_id=tid, t0=0.0)
        """,
        rules=["TC09"],
    )
    assert rules_of(active) == ["TC09"]
    assert "SPAN_CATALOG" in active[0].message


def test_tc09_catalogued_names_and_dynamic_names_are_clean(tmp_path):
    active, _ = check(
        tmp_path,
        """
        from p2p_llm_tunnel_tpu.utils.tracing import global_tracer

        def emit(tid, name):
            global_tracer.add_span("engine.request", trace_id=tid, t0=0.0)
            global_tracer.add_event("engine.first_token", trace_id=tid)
            global_tracer.add_event(name, trace_id=tid)  # non-literal: skipped
        """,
        rules=["TC09"],
    )
    assert active == []


def test_tc09_emission_inside_jitted_function_is_flagged(tmp_path):
    """Span emission is host-only: a recorder call inside a function this
    module jits (or scans) is a tracer error at best, a per-step host sync
    at worst — flagged even when the span name itself is legal."""
    active, _ = check(
        tmp_path,
        """
        import jax
        from p2p_llm_tunnel_tpu.utils.tracing import global_tracer

        def step(x):
            global_tracer.add_event("engine.first_token", trace_id="ab")
            return x + 1

        fast = jax.jit(step)
        """,
        rules=["TC09"],
    )
    assert rules_of(active) == ["TC09"]
    assert "host-only" in active[0].message


def test_tc09_emission_inside_scanned_function_is_flagged(tmp_path):
    active, _ = check(
        tmp_path,
        """
        import jax
        from p2p_llm_tunnel_tpu.utils.tracing import global_tracer

        def body(carry, x):
            global_tracer.add_span("engine.decode_burst", trace_id=None,
                                   t0=0.0)
            return carry, x

        def run(xs):
            return jax.lax.scan(body, 0, xs)
        """,
        rules=["TC09"],
    )
    assert rules_of(active) == ["TC09"]


def test_tc09_waiver_suppresses(tmp_path):
    active, waived = check(
        tmp_path,
        """
        from p2p_llm_tunnel_tpu.utils.tracing import global_tracer

        def emit(tid):
            global_tracer.add_event(
                "adhoc.probe", trace_id=tid,
            )  # tunnelcheck: disable=TC09  one-off debugging probe
        """,
        rules=["TC09"],
    )
    assert active == []
    assert rules_of(waived) == ["TC09"]


def test_tc09_emit_sites_match_the_shipped_catalog():
    """The repo's own emit sites (proxy, serve, engine) stay aligned with
    SPAN_CATALOG — the narrow self-run gate for TC09."""
    active, _ = run_paths(
        [
            REPO_ROOT / "p2p_llm_tunnel_tpu" / "endpoints",
            REPO_ROOT / "p2p_llm_tunnel_tpu" / "engine",
            REPO_ROOT / "p2p_llm_tunnel_tpu" / "utils",
        ],
        rules=["TC09"],
    )
    assert active == [], [v.render(REPO_ROOT) for v in active]


def test_tc08_self_run_every_field_wired_or_waived():
    """The shipped EngineConfig stays rot-free: every field has a serve
    flag or carries a reasoned waiver (the self-run gate for TC08,
    narrower and faster than the full-tree self-run above)."""
    active, waived = run_paths(
        [
            REPO_ROOT / "p2p_llm_tunnel_tpu" / "engine" / "engine.py",
            REPO_ROOT / "p2p_llm_tunnel_tpu" / "cli.py",
        ],
        rules=["TC08"],
    )
    assert active == [], [v.render(REPO_ROOT) for v in active]
    # The deliberate env/programmatic-only fields stay visible as waivers,
    # not silently absent.
    waived_fields = {v.message.split()[0] for v in waived}
    assert "EngineConfig.min_prefill_bucket" in waived_fields
    assert "EngineConfig.prefix_tail_buckets" in waived_fields


# ---------------------------------------------------------------------------
# TC10 — every queue/buffer on the frame-mux path declares its bound (ISSUE 7)
# ---------------------------------------------------------------------------


def test_tc10_unbounded_queue_and_deque_flagged_in_scope(tmp_path):
    active, _ = check(
        tmp_path,
        """
        import asyncio
        from collections import deque

        events = asyncio.Queue()
        backlog = deque()
        """,
        filename="endpoints/snippet.py",
        rules=["TC10"],
    )
    assert rules_of(active) == ["TC10", "TC10"]
    assert "backpressure" in active[0].message


def test_tc10_explicitly_unbounded_still_flags(tmp_path):
    """Literal maxsize=0 / maxlen=None assert unboundedness without naming
    the compensating mechanism — say it in a waiver instead."""
    active, _ = check(
        tmp_path,
        """
        import asyncio
        import collections

        q = asyncio.Queue(maxsize=0)
        d = collections.deque(maxlen=None)
        """,
        filename="transport/snippet.py",
        rules=["TC10"],
    )
    assert rules_of(active) == ["TC10", "TC10"]
    assert "explicitly unbounded" in active[0].message


def test_tc10_bounded_constructions_are_clean(tmp_path):
    active, _ = check(
        tmp_path,
        """
        import asyncio
        from collections import deque

        CAP = 64
        q1 = asyncio.Queue(maxsize=256)
        q2 = asyncio.Queue(CAP)
        d1 = deque(maxlen=8)
        d2 = deque([], 8)
        """,
        filename="protocol/snippet.py",
        rules=["TC10"],
    )
    assert active == []


def test_tc10_out_of_scope_dirs_are_exempt(tmp_path):
    """engine/ (and anything else off the frame-mux path) is out of scope:
    its per-request queues are bounded by max_new_tokens per stream and
    audited by the serving-path rules."""
    active, _ = check(
        tmp_path,
        """
        import asyncio

        q = asyncio.Queue()
        """,
        filename="engine/snippet.py",
        rules=["TC10"],
    )
    assert active == []


def test_tc10_waiver_names_the_backpressure_provider(tmp_path):
    active, waived = check(
        tmp_path,
        """
        import asyncio

        q = asyncio.Queue()  # tunnelcheck: disable=TC10  bounded in bytes by FLOW credit
        """,
        filename="endpoints/snippet.py",
        rules=["TC10"],
    )
    assert active == []
    assert rules_of(waived) == ["TC10"]


# ---------------------------------------------------------------------------
# TC11 — retry/backoff loops bounded + jittered (ISSUE 8)
# ---------------------------------------------------------------------------


def test_tc11_uncapped_unjittered_retry_loop_flags_both(tmp_path):
    """The reference's bare exponential: grows without bound AND re-dials
    a whole fleet in lockstep — one violation for each missing property."""
    active, _ = check(
        tmp_path,
        """
        import asyncio

        async def reconnect(attempt_fn):
            attempt = 0
            while True:
                attempt += 1
                try:
                    await attempt_fn()
                    return
                except Exception:
                    pass
                backoff = 2.0 * (2 ** (attempt - 1))
                await asyncio.sleep(backoff)
        """,
        filename="transport/snippet.py",
        rules=["TC11"],
    )
    assert rules_of(active) == ["TC11", "TC11"]
    assert "without a bound" in active[0].message
    assert "jitter" in active[1].message


def test_tc11_self_doubling_augassign_is_growth(tmp_path):
    active, _ = check(
        tmp_path,
        """
        import asyncio
        import random

        async def redial():
            backoff = 0.1
            while True:
                backoff *= 2
                backoff *= 1.0 + random.uniform(0.0, 0.25)
                await asyncio.sleep(backoff)
        """,
        filename="endpoints/snippet.py",
        rules=["TC11"],
    )
    # Jittered, but `backoff *= 2` has no cap.
    assert rules_of(active) == ["TC11"]
    assert "without a bound" in active[0].message


def test_tc11_capped_jittered_loop_is_clean(tmp_path):
    active, _ = check(
        tmp_path,
        """
        import asyncio
        import random

        async def reconnect(attempt_fn):
            attempt = 0
            while True:
                attempt += 1
                try:
                    await attempt_fn()
                    return
                except Exception:
                    pass
                backoff = min(2.0 * (2 ** (attempt - 1)), 60.0)
                backoff *= 1.0 + random.uniform(0.0, 0.25)
                await asyncio.sleep(backoff)
        """,
        filename="snippet/cli.py",
        rules=["TC11"],
    )
    assert active == []


def test_tc11_bounded_for_range_counts_as_the_attempt_bound(tmp_path):
    """`for attempt in range(N)` bounds attempts even when the backoff
    expression itself is a bare exponential — but jitter is still required
    (and present here via the wait_for timeout spelling)."""
    active, _ = check(
        tmp_path,
        """
        import asyncio
        import random

        async def dial(stop):
            for attempt in range(1, 4):
                backoff = 1.0 * (2 ** (attempt - 1))
                backoff *= 1.0 + random.uniform(0.0, 0.5)
                try:
                    await asyncio.wait_for(stop.wait(), backoff)
                except asyncio.TimeoutError:
                    pass
        """,
        filename="transport/snippet.py",
        rules=["TC11"],
    )
    assert active == []


def test_tc11_fixed_interval_loops_are_out_of_scope(tmp_path):
    """Keepalives and probers sleep a CONSTANT interval — no growth, no
    retry semantics, no finding."""
    active, _ = check(
        tmp_path,
        """
        import asyncio

        PING_INTERVAL = 10.0

        async def keepalive(ch):
            while True:
                await asyncio.sleep(PING_INTERVAL)
                await ch.ping()
        """,
        filename="endpoints/snippet.py",
        rules=["TC11"],
    )
    assert active == []


def test_tc11_sleep_in_nested_def_does_not_attribute_to_outer_loop(tmp_path):
    """A callback defined inside a loop runs when called, not per
    iteration — its sleep belongs to no enclosing retry loop."""
    active, _ = check(
        tmp_path,
        """
        import asyncio

        async def outer(items):
            while True:
                n = 2 ** 3

                async def cb():
                    await asyncio.sleep(0.1)

                await register(cb)
        """,
        filename="transport/snippet.py",
        rules=["TC11"],
    )
    assert active == []


def test_tc11_out_of_scope_dirs_are_exempt(tmp_path):
    active, _ = check(
        tmp_path,
        """
        import asyncio

        async def poll(attempt):
            while True:
                attempt += 1
                backoff = 2 ** attempt
                await asyncio.sleep(backoff)
        """,
        filename="engine/snippet.py",
        rules=["TC11"],
    )
    assert active == []


def test_tc11_waiver_names_the_bound(tmp_path):
    active, waived = check(
        tmp_path,
        """
        import asyncio

        async def rto_loop(tries):
            while True:
                tries += 1
                rto = 0.2 * (2 ** min(tries, 4))
                await asyncio.sleep(rto)  # tunnelcheck: disable=TC11  exponent clamped at 2^4, jitter-free: pacing follows the measured RTT
        """,
        filename="transport/snippet.py",
        rules=["TC11"],
    )
    assert active == []
    assert rules_of(waived) == ["TC11", "TC11"]


def test_tc11_repo_retry_loops_are_detected_not_just_absent():
    """Meta-fixture: strip the jitter multiply out of the REAL
    cli.run_with_retry source and TC11 must fire — proving the shipped
    loop passes because it satisfies the rule, not because the detector
    misses it."""
    import re

    src = (REPO_ROOT / "p2p_llm_tunnel_tpu" / "cli.py").read_text()
    stripped = re.sub(
        r"backoff \*= 1\.0 \+ random\.uniform\(0\.0, 0\.25\)", "pass", src
    )
    assert stripped != src
    active, _ = check_path_text(stripped)
    assert any(
        v.rule == "TC11" and "jitter" in v.message for v in active
    ), "de-jittered run_with_retry must trip TC11"


def check_path_text(text: str):
    """Run only TC11 over literal file text named cli.py (scope by name)."""
    import tempfile
    from pathlib import Path as _P

    with tempfile.TemporaryDirectory() as d:
        f = _P(d) / "cli.py"
        f.write_text(text)
        return run_paths([f], rules=["TC11"])


# ---------------------------------------------------------------------------
# TC12 — labeled Prometheus series only through the bounded registry
# ---------------------------------------------------------------------------


def test_tc12_flags_fstring_label_interpolation(tmp_path):
    active, _ = check(
        tmp_path,
        '''
        def render(tenant, v):
            return f'tenant_tokens_total{{tenant="{tenant}"}} {v}'
        ''',
        rules=["TC12"],
    )
    assert rules_of(active) == ["TC12"]
    assert "set_labeled_gauge" in active[0].message


def test_tc12_flags_percent_and_format_interpolation(tmp_path):
    active, _ = check(
        tmp_path,
        '''
        def render(pid, v):
            a = 'x{peer="%s"} %g' % (pid, v)
            b = 'x{peer="{}"} {}'.format(pid, v)
            return a, b
        ''',
        rules=["TC12"],
    )
    assert rules_of(active) == ["TC12", "TC12"]


def test_tc12_ignores_plain_literals_and_unrelated_fstrings(tmp_path):
    # Non-interpolated label literals (test assertions against exposition
    # output) carry no cardinality risk; f-strings without label syntax
    # in their CONSTANT parts are someone else's business.
    active, _ = check(
        tmp_path,
        '''
        def asserts(text, q):
            assert 'tenant_in_flight{tenant="a"} 1' in text
            assert f'quantile="{q}"' in text
            return f"plain {q} interpolation"
        ''',
        rules=["TC12"],
    )
    assert active == []


def test_tc12_waiver_and_registry_exemption(tmp_path):
    active, waived = check(
        tmp_path,
        '''
        def render(t):
            return f'x{{tenant="{t}"}} 1'  # tunnelcheck: disable=TC12  fixture
        ''',
        rules=["TC12"],
    )
    assert active == [] and rules_of(waived) == ["TC12"]
    # The registry module itself is the ONE legal interpolation site.
    active, _ = check(
        tmp_path,
        '''
        def prom_sample(name, k, v, val):
            return f'{name}{{{k}="{v}"}} {val}'
        ''',
        filename="p2p_llm_tunnel_tpu/utils/metrics.py",
        rules=["TC12"],
    )
    assert active == []


def test_tc12_bounded_helper_is_actually_bounded():
    """The helpers TC12 points at must honor their cap: past LABELED_CAP
    distinct labels the least-recently-set is evicted, so the rule's
    cardinality story is enforced at runtime too."""
    from p2p_llm_tunnel_tpu.utils.metrics import LABELED_CAP, Metrics

    m = Metrics()
    for i in range(LABELED_CAP + 10):
        m.set_labeled_gauge("fleet_peer_scrape_stale", "peer",
                            f"p{i:04d}", float(i))
    got = m.labeled_gauge("fleet_peer_scrape_stale")
    assert len(got) == LABELED_CAP
    assert "p0000" not in got and f"p{LABELED_CAP + 9:04d}" in got


# ---------------------------------------------------------------------------
# TC13 — await-atomicity: shared RMW across a suspension point
# ---------------------------------------------------------------------------

PEERS_FIXTURE = "p2p_llm_tunnel_tpu/endpoints/fixture_peers.py"


def test_tc13_stale_local_rmw_across_await(tmp_path):
    active, _ = check(
        tmp_path,
        """
        class Breaker:
            def ok(self):
                return self.failures < 3

            async def probe(self, peer):
                n = self.failures
                await peer.send(b"probe")
                self.failures = n + 1
        """,
        filename=PEERS_FIXTURE,
        rules=["TC13"],
    )
    assert rules_of(active) == ["TC13"]
    assert "stale local `n`" in active[0].message
    assert "failures" in active[0].message


def test_tc13_check_then_act_across_await(tmp_path):
    active, _ = check(
        tmp_path,
        """
        class Breaker:
            def ok(self):
                return self.failures < 3

            async def probe(self, peer):
                if self.failures >= 3:
                    await peer.send(b"probe")
                    self.failures = 0
        """,
        filename=PEERS_FIXTURE,
        rules=["TC13"],
    )
    assert rules_of(active) == ["TC13"]


def test_tc13_reread_after_await_is_clean(tmp_path):
    """The check-again idiom: a fresh read after the suspension refreshes
    the premise, so the write is NOT torn."""
    active, _ = check(
        tmp_path,
        """
        class Breaker:
            def ok(self):
                return self.failures < 3

            async def probe(self, peer):
                await peer.send(b"probe")
                self.failures = self.failures + 1
        """,
        filename=PEERS_FIXTURE,
        rules=["TC13"],
    )
    assert active == []


def test_tc13_lock_held_rmw_is_clean(tmp_path):
    active, _ = check(
        tmp_path,
        """
        class Breaker:
            def ok(self):
                return self.failures < 3

            async def probe(self, peer):
                async with self._lock:
                    n = self.failures
                    await peer.send(b"probe")
                    self.failures = n + 1
        """,
        filename=PEERS_FIXTURE,
        rules=["TC13"],
    )
    assert active == []


def test_tc13_single_accessor_attr_is_exempt(tmp_path):
    """An attribute only ONE function ever touches has a single-writer
    contract by construction — no second accessor can interleave."""
    active, _ = check(
        tmp_path,
        """
        class Loop:
            async def run(self, peer):
                n = self._only_here
                await peer.send(b"x")
                self._only_here = n + 1
        """,
        filename=PEERS_FIXTURE,
        rules=["TC13"],
    )
    assert active == []


def test_tc13_blind_write_after_await_is_clean(tmp_path):
    """A write whose value does not depend on a pre-await read (keepalive
    timestamp stamping) is not a read-modify-write."""
    active, _ = check(
        tmp_path,
        """
        import time

        class Keepalive:
            def read(self):
                return self._sent_at

            async def run(self, peer):
                while True:
                    await peer.sleep(1)
                    self._sent_at = time.monotonic()
                    await peer.send(b"ping")
        """,
        filename=PEERS_FIXTURE,
        rules=["TC13"],
    )
    assert active == []


def test_tc13_waiver_names_the_owning_task(tmp_path):
    active, waived = check(
        tmp_path,
        """
        class Loop:
            def read(self):
                return self._progress

            async def run(self, peer):
                n = self._progress
                await peer.send(b"x")
                self._progress = n + 1  # tunnelcheck: disable=TC13  single-writer: the engine loop task owns decode progress
        """,
        filename=PEERS_FIXTURE,
        rules=["TC13"],
    )
    assert active == []
    assert rules_of(waived) == ["TC13"]


def test_tc13_meta_breaker_half_open_wedge(tmp_path):
    """The rule reproduces its incident (the TC02/TC11 pattern): the PR 8
    review breaker bug — half-open bookkeeping decided from a
    consec_failures read taken BEFORE the probe dispatch's await, so a
    concurrent failure in the await window was silently erased."""
    active, _ = check(
        tmp_path,
        """
        CB_THRESHOLD = 3

        class PeerSet:
            def dispatchable(self, link):
                return link.consec_failures < CB_THRESHOLD

            async def half_open_probe(self, link, msg):
                tripped = link.consec_failures >= CB_THRESHOLD
                await link.channel.send(msg)
                if tripped:
                    link.consec_failures = 0
        """,
        filename=PEERS_FIXTURE,
        rules=["TC13"],
    )
    assert rules_of(active) == ["TC13"]
    assert "consec_failures" in active[0].message
    assert "interleave" in active[0].message


# ---------------------------------------------------------------------------
# TC14 — header taint must pass a registered sanitizer before trusted sinks
# ---------------------------------------------------------------------------

API_FIXTURE = "p2p_llm_tunnel_tpu/endpoints/fixture_api.py"


def test_tc14_meta_pre_pr7_tenant_minting(tmp_path):
    """The rule reproduces its incident: the pre-PR-7 ingress took the raw
    x-tunnel-tenant header bytes as the scheduler identity AND the metric
    label — the exact minting hole parse_tenant closed."""
    active, _ = check(
        tmp_path,
        """
        async def handle(req, payload, global_metrics):
            tenant = ""
            for k, v in req.headers.items():
                if k.lower() == "x-tunnel-tenant":
                    tenant = v
            kwargs = {}
            if tenant:
                kwargs["tenant"] = tenant
                global_metrics.tenant_begin(tenant)
            return kwargs
        """,
        filename=API_FIXTURE,
        rules=["TC14"],
    )
    assert rules_of(active) == ["TC14", "TC14"]
    assert any("scheduler tenant identity" in v.message for v in active)
    assert any("per-tenant accounting" in v.message for v in active)


def test_tc14_sanitized_ingress_is_clean(tmp_path):
    active, _ = check(
        tmp_path,
        """
        from p2p_llm_tunnel_tpu.protocol.frames import parse_tenant

        async def handle(req, global_metrics):
            tenant = parse_tenant(req.headers)
            kwargs = {}
            if tenant:
                kwargs["tenant"] = tenant
                global_metrics.tenant_begin(tenant)
            return kwargs
        """,
        filename=API_FIXTURE,
        rules=["TC14"],
    )
    assert active == []


def test_tc14_headers_param_seeds_taint(tmp_path):
    active, _ = check(
        tmp_path,
        """
        def account(headers, global_metrics):
            for k, v in headers.items():
                if k == "x-tunnel-tenant":
                    global_metrics.tenant_tokens(v)
        """,
        filename=API_FIXTURE,
        rules=["TC14"],
    )
    assert rules_of(active) == ["TC14"]


def test_tc14_labeled_gauge_and_log_interpolation_sinks(tmp_path):
    active, _ = check(
        tmp_path,
        """
        def publish(req, metrics, log):
            raw = req.headers.get("x-tunnel-tenant", "")
            metrics.set_labeled_gauge("tenant_inflight", "tenant", raw, 1.0)
            log.warning(f"tenant {raw} over limit")
            log.error("tenant {t} over limit".format(t=raw))
            log.warning("tenant %s over limit", raw)  # lazy args: exempt
        """,
        filename=API_FIXTURE,
        rules=["TC14"],
    )
    assert rules_of(active) == ["TC14", "TC14", "TC14"]
    assert any("labeled-metrics" in v.message for v in active)
    assert any("log interpolation" in v.message for v in active)


def test_tc14_relay_target_sink(tmp_path):
    active, _ = check(
        tmp_path,
        """
        async def relay(req, signaling):
            target = req.headers.get("x-relay-to", "")
            await signaling.send({"type": "relay", "to": target})
        """,
        filename=API_FIXTURE,
        rules=["TC14"],
    )
    assert rules_of(active) == ["TC14"]
    assert "relay" in active[0].message


def test_tc14_numeric_coercion_sanitizes(tmp_path):
    active, _ = check(
        tmp_path,
        """
        def weight(headers, scheduler):
            w = int(headers.get("x-weight", "1"))
            scheduler.charge_tokens(w, 1)
        """,
        filename=API_FIXTURE,
        rules=["TC14"],
    )
    assert active == []


def test_tc14_waiver(tmp_path):
    active, waived = check(
        tmp_path,
        """
        def account(headers, global_metrics):
            v = headers.get("x-tunnel-tenant", "")
            global_metrics.tenant_begin(v)  # tunnelcheck: disable=TC14  fixture: proxy-stamped header, trusted inside the tunnel
        """,
        filename=API_FIXTURE,
        rules=["TC14"],
    )
    assert active == []
    assert rules_of(waived) == ["TC14"]


def test_tc14_out_of_scope_tree_is_free(tmp_path):
    active, _ = check(
        tmp_path,
        """
        def account(headers, global_metrics):
            global_metrics.tenant_begin(headers.get("t", ""))
        """,
        filename="somewhere_else.py",
        rules=["TC14"],
    )
    assert active == []


# ---------------------------------------------------------------------------
# TC15 — resource lifecycle: release on every exit path, aclose() included
# ---------------------------------------------------------------------------

ENG_FIXTURE = "p2p_llm_tunnel_tpu/engine/fixture_lifecycle.py"


def test_tc15_meta_pre_pr6_finish_after_final_yield(tmp_path):
    """The rule reproduces its incident: pre-PR-6 generate() emitted the
    request span AFTER the yield loop — a consumer that stops iterating
    closes the generator at the yield (GeneratorExit) and the emission
    never runs, logging every normal finish as a leaked/cancelled span."""
    active, _ = check(
        tmp_path,
        """
        async def generate(self, req, queue, global_tracer):
            span = new_span_id()
            while True:
                event = await queue.get()
                if event is None:
                    break
                yield event
            global_tracer.add_span(
                "engine.request", trace_id=req.trace, span_id=span,
            )
        """,
        filename=ENG_FIXTURE,
        rules=["TC15"],
    )
    assert rules_of(active) == ["TC15"]
    assert "aclose" in active[0].message
    assert "span" in active[0].message


def test_tc15_finally_release_is_clean(tmp_path):
    active, _ = check(
        tmp_path,
        """
        async def generate(self, req, queue, global_tracer):
            span = new_span_id()
            try:
                while True:
                    event = await queue.get()
                    if event is None:
                        return
                    yield event
            finally:
                global_tracer.add_span(
                    "engine.request", trace_id=req.trace, span_id=span,
                )
        """,
        filename=ENG_FIXTURE,
        rules=["TC15"],
    )
    assert active == []


def test_tc15_inflight_registry_across_await(tmp_path):
    active, _ = check(
        tmp_path,
        """
        async def fetch(self, link, sid, q):
            link.pending[sid] = q
            await link.channel.send(b"x")
            link.pending.pop(sid, None)
        """,
        filename=ENG_FIXTURE,
        rules=["TC15"],
    )
    assert rules_of(active) == ["TC15"]
    assert "link.pending" in active[0].message


def test_tc15_inflight_registry_finally_is_clean(tmp_path):
    active, _ = check(
        tmp_path,
        """
        async def fetch(self, link, sid, q):
            link.pending[sid] = q
            try:
                await link.channel.send(b"x")
            finally:
                link.pending.pop(sid, None)
        """,
        filename=ENG_FIXTURE,
        rules=["TC15"],
    )
    assert active == []


def test_tc15_straight_line_release_is_clean(tmp_path):
    active, _ = check(
        tmp_path,
        """
        def requeue(self, sid, q):
            self.pending[sid] = q
            self.counts[sid] = self.counts.get(sid, 0) + 1
            self.pending.pop(sid)
        """,
        filename=ENG_FIXTURE,
        rules=["TC15"],
    )
    assert active == []


def test_tc15_local_buffer_is_not_a_registry(tmp_path):
    """A bare-name dict local to the frame (pending_lp accumulation) dies
    with the frame — only parameters count as passed-in shared registries."""
    active, _ = check(
        tmp_path,
        """
        async def stream(self, queue):
            pending_lp = {}
            while True:
                i = await queue.get()
                if i is None:
                    break
                pending_lp[i] = i
                yield i
        """,
        filename=ENG_FIXTURE,
        rules=["TC15"],
    )
    assert active == []


def test_tc15_param_registry_counts(tmp_path):
    active, _ = check(
        tmp_path,
        """
        def plan(wave, inflight):
            for rid in wave:
                inflight[rid] = rid
            return wave
        """,
        filename=ENG_FIXTURE,
        rules=["TC15"],
    )
    assert rules_of(active) == ["TC15"]


def test_tc15_delegated_closure_release_satisfies(tmp_path):
    """A nested closure owning the release (drop_stream/finish_span) is
    the delegated-owner contract the proxy dispatch path uses."""
    active, _ = check(
        tmp_path,
        """
        async def dispatch(self, link, sid, q):
            link.pending[sid] = q

            def drop_stream():
                link.pending.pop(sid, None)

            try:
                await link.channel.send(b"x")
            except Exception:
                drop_stream()
                raise
            return drop_stream
        """,
        filename=ENG_FIXTURE,
        rules=["TC15"],
    )
    assert active == []


def test_tc15_crypto_box_open_is_not_an_acquire(tmp_path):
    active, _ = check(
        tmp_path,
        """
        async def decrypt(self, data):
            plain = self._box.open(data)
            await self.deliver(plain)
        """,
        filename="p2p_llm_tunnel_tpu/transport/fixture_crypto.py",
        rules=["TC15"],
    )
    assert active == []


def test_tc15_waiver_names_releasing_owner(tmp_path):
    active, waived = check(
        tmp_path,
        """
        def register(self, sid, q):
            self.pending[sid] = q  # tunnelcheck: disable=TC15  released by the reader task's RES_END arm
        """,
        filename=ENG_FIXTURE,
        rules=["TC15"],
    )
    assert active == []
    assert rules_of(waived) == ["TC15"]


def test_tc15_detached_stream_registry_journal_leak(tmp_path):
    """ISSUE 13: the detached-stream registry is in TC15's vocabulary.
    This fixture reconstructs the journal-leak shape — a stream
    registered for resume whose grace-expiry/consumer-gone path never
    releases it: the replay journal's bytes stay resident forever for a
    stream nobody can resume (and the consumer closing the generator at
    the yield is exactly how the path is reached)."""
    active, _ = check(
        tmp_path,
        """
        async def park_for_resume(self, relay, queue):
            self._detached[relay.token] = relay
            while True:
                chunk = await queue.get()
                if chunk is None:
                    return
                relay.journal.append(chunk)
                yield chunk
        """,
        filename=ENG_FIXTURE,
        rules=["TC15"],
    )
    assert rules_of(active) == ["TC15"]
    assert "_detached" in active[0].message


def test_tc15_detached_stream_registry_finally_release_is_clean(tmp_path):
    active, _ = check(
        tmp_path,
        """
        async def park_for_resume(self, relay, queue):
            self._detached[relay.token] = relay
            try:
                while True:
                    chunk = await queue.get()
                    if chunk is None:
                        return
                    relay.journal.append(chunk)
                    yield chunk
            finally:
                self._detached.pop(relay.token, None)
        """,
        filename=ENG_FIXTURE,
        rules=["TC15"],
    )
    assert active == []


# ---------------------------------------------------------------------------
# TC16 — flight/postmortem schema registries + ops routing via ops_route
# ---------------------------------------------------------------------------


def test_tc16_flags_unknown_flight_field(tmp_path):
    # The registry resolves from the REPO's own utils/flight.py even when
    # the fixture tree doesn't carry a copy (the TC06 fallback pattern).
    active, _ = check(
        tmp_path,
        """
        def loop_tick(flight):
            flight.record_iteration(queue_depth=3, queue_dept=4)
        """,
        rules=["TC16"],
    )
    assert rules_of(active) == ["TC16"]
    assert "queue_dept" in active[0].message
    assert "FLIGHT_SCHEMA" in active[0].message


def test_tc16_declared_flight_fields_are_clean(tmp_path):
    active, _ = check(
        tmp_path,
        """
        def loop_tick(flight):
            flight.record_iteration(
                queue_depth=3, budget_tokens=64, decode_steps=8,
            )
        """,
        rules=["TC16"],
    )
    assert active == []


def test_tc16_flags_undeclared_postmortem_extra_key(tmp_path):
    active, _ = check(
        tmp_path,
        """
        def on_incident(bb):
            bb.capture("manual", extra={"trigger": "x", "vibes": 1})
        """,
        rules=["TC16"],
    )
    assert rules_of(active) == ["TC16"]
    assert "vibes" in active[0].message
    assert "POSTMORTEM_SCHEMA" in active[0].message


def test_tc16_flags_handrolled_ops_path_matching_in_endpoints(tmp_path):
    # All three hand-rolled shapes the pre-ISSUE-9 copies used: equality,
    # startswith, and a raw query-token membership test against .path.
    active, _ = check(
        tmp_path,
        """
        async def handler(req):
            if req.path == "/healthz":
                return 1
            if req.path.startswith("/metrics"):
                return 2
            if "trace=1" in req.path:
                return 3
        """,
        filename="p2p_llm_tunnel_tpu/endpoints/custom_ops.py",
        rules=["TC16"],
    )
    assert rules_of(active) == ["TC16", "TC16", "TC16"]
    assert "ops_route" in active[0].message


def test_tc16_ops_route_flag_set_and_non_endpoint_files_are_clean(tmp_path):
    # The sanctioned pattern — flags tested against ops_route's returned
    # set — and the same strings outside endpoints/ (tests, scripts,
    # client-side fetch paths) are out of scope.
    active, _ = check(
        tmp_path,
        """
        from p2p_llm_tunnel_tpu.endpoints.http11 import ops_route

        async def handler(req):
            route = ops_route(req.method, req.path)
            if route is not None and "trace=1" in route[1]:
                return 1
        """,
        filename="p2p_llm_tunnel_tpu/endpoints/custom_ops.py",
        rules=["TC16"],
    )
    assert active == []
    active, _ = check(
        tmp_path,
        """
        async def scrape(fetch):
            return await fetch("/healthz?trace=1")

        def assert_path(path):
            assert path == "/healthz"
        """,
        filename="scripts/poker.py",
        rules=["TC16"],
    )
    assert active == []


def test_tc16_http11_is_the_one_legal_matcher_and_waiver_works(tmp_path):
    # ops_route's own implementation necessarily string-matches.
    active, _ = check(
        tmp_path,
        """
        def ops_route(method, path):
            base = path.partition("?")[0]
            if base not in ("/healthz", "/metrics"):
                return None
            return base[1:]
        """,
        filename="p2p_llm_tunnel_tpu/endpoints/http11.py",
        rules=["TC16"],
    )
    assert active == []
    active, waived = check(
        tmp_path,
        """
        async def handler(req):
            if req.path == "/healthz":  # tunnelcheck: disable=TC16  fixture
                return 1
        """,
        filename="p2p_llm_tunnel_tpu/endpoints/custom_ops.py",
        rules=["TC16"],
    )
    assert active == [] and rules_of(waived) == ["TC16"]


def test_tc16_runtime_registry_agrees_with_static_rule():
    """The runtime guard TC16 statically mirrors: record_iteration
    rejects undeclared fields, capture builds exactly the declared
    schema (both raise loudly on drift)."""
    from p2p_llm_tunnel_tpu.utils.flight import (
        FLIGHT_SCHEMA,
        POSTMORTEM_SCHEMA,
        BlackBox,
        FlightRecorder,
    )

    rec = FlightRecorder(capacity=4)
    with pytest.raises(ValueError):
        rec.record_iteration(not_a_field=1)  # tunnelcheck: disable=TC16  deliberate drift: pins the runtime guard
    rec.record_iteration(**{k: 0 for k in FLIGHT_SCHEMA if k != "iter"})
    bundle = BlackBox(directory="").capture("manual")
    assert set(bundle) == set(POSTMORTEM_SCHEMA)


# ---------------------------------------------------------------------------
# SARIF export, --list-rules pin, TC00 counting, parallel + changed-only
# ---------------------------------------------------------------------------


# ---------------------------------------------------------------------------
# TC17 — dispatch-site program kinds must be warmup-plan-reachable
# ---------------------------------------------------------------------------


def test_tc17_flags_unwarmed_dispatch_kind(tmp_path):
    """The width-hint hole class one layer earlier: a program kind that
    exists only at a dispatch site cannot even be enumerated by the
    warmup plan — the first request reaching it cold-compiles mid-serve."""
    active, _ = check(
        tmp_path,
        """
        class Eng:
            def warmup_plan(self):
                return [("decode", (128, 8))]

            def _dispatch_chunk_rows(self, rows, t):
                self._note_program("chunk", (t, 128), 0.1)
        """,
        rules=["TC17"],
    )
    assert rules_of(active) == ["TC17"]
    assert "'chunk'" in active[0].message


def test_tc17_plan_tuple_and_warm_helper_kinds_are_reachable(tmp_path):
    """Both warm spellings count: a ("kind", shape) tuple in the plan
    enumeration AND a _warm_* helper's own _note_program call."""
    active, _ = check(
        tmp_path,
        """
        class Eng:
            def warmup_plan(self):
                return [("decode", (128, 8)), ("chunk", (16, 128))]

            def _warm_ragged_program(self, tot):
                self._note_program("ragged", (tot,), 0.0)

            def _dispatch_decode(self):
                self._note_program("decode", (128, 8), 0.1)

            def _dispatch_chunk_rows(self, rows, t):
                self._note_program("chunk", (t, 128), 0.1)

            def _dispatch_ragged_rows(self, rows):
                self._note_program("ragged", (64,), 0.1)
        """,
        rules=["TC17"],
    )
    assert active == []


def test_tc17_program_key_spelling_is_a_dispatch_site_too(tmp_path):
    """Minting a key via _program_key directly (ad-hoc accounting without
    _note_program) is the same reachability hole — both spellings count."""
    active, _ = check(
        tmp_path,
        """
        class Eng:
            def warmup_plan(self):
                return [("decode", (128, 8))]

            def _dispatch_embed(self, rows):
                key = _program_key("embed", (len(rows),))
                self._ready.add(key)
        """,
        rules=["TC17"],
    )
    assert rules_of(active) == ["TC17"]
    assert "'embed'" in active[0].message


def test_tc17_ifexp_branches_checked_individually(tmp_path):
    """The `"prefill_echo" if echo else "prefill"` dispatch shape: the
    warmed branch must not launder the unwarmed one."""
    active, _ = check(
        tmp_path,
        """
        class Eng:
            def _warm_prefill_program(self, w):
                self._note_program("prefill", (w,), 0.0)

            def _dispatch_prefill_batch(self, runs, t, echo):
                self._note_program(
                    "prefill_echo" if echo else "prefill", (t,), 0.1
                )
        """,
        rules=["TC17"],
    )
    assert rules_of(active) == ["TC17"]
    assert "'prefill_echo'" in active[0].message


def test_tc17_waiver_and_out_of_scope_files(tmp_path):
    """A waiver naming the first-use contract suppresses; files that never
    call _note_program are out of scope entirely."""
    active, waived = check(
        tmp_path,
        """
        class Eng:
            def _dispatch_prefill_batch(self, runs, t, echo):
                self._note_program("prefill_echo", (t,), 0.1)  # tunnelcheck: disable=TC17  eval-only feature, first-use compile by contract
        """,
        rules=["TC17"],
    )
    assert active == [] and rules_of(waived) == ["TC17"]
    active, _ = check(
        tmp_path,
        """
        def unrelated():
            plan = [("decode", (128, 8))]
            return plan
        """,
        filename="clean.py",
        rules=["TC17"],
    )
    assert active == []


def test_tc17_warm_closure_inside_dispatcher_does_not_launder(tmp_path):
    """A warm-NAMED closure nested inside a dispatch function is not a
    plan generator — its literals must not mark the kind reachable (and
    its own _note_program call is a second unwarmed dispatch site)."""
    active, _ = check(
        tmp_path,
        """
        class Eng:
            def _dispatch_spec(self):
                def _warm_fake():
                    self._note_program("spec", (128,), 0.0)
                self._note_program("spec", (128,), 0.1)
        """,
        rules=["TC17"],
    )
    assert rules_of(active) == ["TC17", "TC17"]


def test_tc17_engine_self_run_has_only_the_echo_waiver():
    """The real engine is TC17-clean modulo the documented prefill_echo
    first-use contract — the ragged/chunk/decode/spec/prefill kinds are
    all reachable from warmup_plan()."""
    eng = REPO_ROOT / "p2p_llm_tunnel_tpu" / "engine" / "engine.py"
    active, waived = run_paths([eng], rules=["TC17"])
    assert active == []
    assert rules_of(waived) == ["TC17"]
    assert any("prefill_echo" in v.message for v in waived)


# ---------------------------------------------------------------------------
# TC18 — KV page bytes must pass the tier-boundary pin check before splice
# ---------------------------------------------------------------------------

SPILL_FIXTURE = "p2p_llm_tunnel_tpu/engine/fixture_spill.py"


def test_tc18_unchecked_page_in_splice_flags(tmp_path):
    """The incident shape: a spill-tier page body spliced straight into
    the pool — int4 bytes landing in an int8 pool decode garbage long
    after the splice."""
    active, _ = check(
        tmp_path,
        """
        def splice(self, items):
            for key, idx, page in items:
                payload = page.payload
                self._pool = self._page_in_op(self._pool, idx, payload)
        """,
        filename=SPILL_FIXTURE,
        rules=["TC18"],
    )
    assert rules_of(active) == ["TC18"]
    assert "verify_page_pin" in active[0].message


def test_tc18_pin_check_reassign_launders(tmp_path):
    """The sanctioned idiom: the checked value REPLACES the unchecked
    binding, so the splice can only see the laundered name."""
    active, _ = check(
        tmp_path,
        """
        def splice(self, items):
            for key, idx, page in items:
                payload = page.payload
                payload = verify_page_pin(payload, page.meta, self._meta)
                self._pool = self._page_in_op(self._pool, idx, payload)
        """,
        filename=SPILL_FIXTURE,
        rules=["TC18"],
    )
    assert active == []


def test_tc18_is_flow_sensitive_not_call_anywhere(tmp_path):
    """A bare verify_page_pin CALL whose result is discarded does not
    launder: the unchecked binding still reaches the splice.  (TC14's
    flow-insensitive lattice cannot make this distinction — the rule's
    reason to exist on the CFG-ordered walk.)"""
    active, _ = check(
        tmp_path,
        """
        def splice(self, items):
            for key, idx, page in items:
                payload = page.payload
                verify_page_pin(payload, page.meta, self._meta)
                self._pool = self._page_in_op(self._pool, idx, payload)
        """,
        filename=SPILL_FIXTURE,
        rules=["TC18"],
    )
    assert rules_of(active) == ["TC18"]


def test_tc18_failed_check_path_excluded_from_join(tmp_path):
    """The engine's page-in loop shape: the except handler drops the page
    to the re-prefill fallback via ``continue``, so its tainted state
    never merges past the try — the splice after it is clean."""
    active, _ = check(
        tmp_path,
        """
        def splice(self, items):
            for key, idx, page in items:
                payload = page.payload
                if self._chaos:
                    payload = dict(page.payload)
                try:
                    payload = verify_page_pin(payload, page.meta, self._m)
                except PagePinError:
                    log.warning("dropped %s", key)
                    continue
                self._pool = self._page_in_op(self._pool, idx, payload)
        """,
        filename=SPILL_FIXTURE,
        rules=["TC18"],
    )
    assert active == []


def test_tc18_payload_param_seeds_and_update_sink(tmp_path):
    """A raw page body crossing a function boundary stays tainted, and
    the jax scatter primitive + .at[].set buffer writes are sinks."""
    active, _ = check(
        tmp_path,
        """
        import jax

        def splice(pool, idx, payload):
            pool = jax.lax.dynamic_update_index_in_dim(
                pool, payload, idx, axis=1
            )
            return pool.at[idx].set(payload)
        """,
        filename=SPILL_FIXTURE,
        rules=["TC18"],
    )
    assert rules_of(active) == ["TC18", "TC18"]
    assert any("dynamic_update_index_in_dim" in v.message for v in active)
    assert any(".at[...].set" in v.message for v in active)


def test_tc18_waiver(tmp_path):
    active, waived = check(
        tmp_path,
        """
        def warm(self):
            page = self.frame.payload
            self._pool = self._page_in_op(self._pool, 0, page)  # tunnelcheck: disable=TC18  loop-local round-trip, never left this process
        """,
        filename=SPILL_FIXTURE,
        rules=["TC18"],
    )
    assert active == []
    assert rules_of(waived) == ["TC18"]


def test_tc18_engine_and_prefix_cache_self_run_clean():
    """The real splice paths are TC18-clean WITHOUT waivers: every
    page-in routes through verify_page_pin before touching the pool."""
    eng = REPO_ROOT / "p2p_llm_tunnel_tpu" / "engine" / "engine.py"
    pfx = REPO_ROOT / "p2p_llm_tunnel_tpu" / "engine" / "prefix_cache.py"
    active, waived = run_paths([eng, pfx], rules=["TC18"])
    assert active == []
    assert rules_of(waived) == []


def test_sarif_2_1_0_shape(tmp_path):
    """Pins the SARIF 2.1.0 shape downstream consumers ingest: version,
    $schema, the rules table (ruleIndex points into it), physical
    locations with SRCROOT-relative URIs, and waived findings carried as
    suppressed results."""
    import json

    from tools.tunnelcheck.core import RULE_SUMMARIES

    bad = tmp_path / "bad.py"
    bad.write_text(
        "import time\n\nasync def f():\n    time.sleep(1)\n"
        "\nasync def g():\n    time.sleep(2)  # tunnelcheck: disable=TC01  fixture\n"
    )
    out = tmp_path / "artifacts" / "lint.sarif"
    rc = tunnelcheck_main([str(bad), "--sarif", str(out)])
    assert rc == 1
    log = json.loads(out.read_text())

    assert log["version"] == "2.1.0"
    assert log["$schema"].endswith("sarif-2.1.0.json")
    run = log["runs"][0]
    driver = run["tool"]["driver"]
    assert driver["name"] == "tunnelcheck"
    rule_ids = [r["id"] for r in driver["rules"]]
    assert rule_ids == sorted(RULE_SUMMARIES)
    assert all(r["shortDescription"]["text"] for r in driver["rules"])

    results = run["results"]
    assert len(results) == 2  # one active, one suppressed
    active = [r for r in results if "suppressions" not in r]
    waived = [r for r in results if "suppressions" in r]
    assert len(active) == 1 and len(waived) == 1
    res = active[0]
    assert res["ruleId"] == "TC01"
    assert rule_ids[res["ruleIndex"]] == "TC01"
    assert res["level"] == "error"
    assert res["message"]["text"]
    loc = res["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"].endswith("bad.py")
    assert loc["artifactLocation"]["uriBaseId"] == "SRCROOT"
    assert loc["region"]["startLine"] == 4
    assert waived[0]["suppressions"][0]["kind"] == "inSource"
    assert run["originalUriBaseIds"]["SRCROOT"]["uri"].startswith("file://")


def test_sarif_includes_tc00(tmp_path):
    import json

    broken = tmp_path / "broken.py"
    broken.write_text("def broken(:\n")
    out = tmp_path / "lint.sarif"
    assert tunnelcheck_main([str(broken), "--sarif", str(out)]) == 1
    log = json.loads(out.read_text())
    assert [r["ruleId"] for r in log["runs"][0]["results"]] == ["TC00"]


def test_list_rules_pinned_against_code_and_readme(capsys):
    """Rule-id drift (docs vs code) fails fast: --list-rules must show
    exactly TC00..TC21, every runnable rule must have a summary, and the
    README rule table must carry a row for every rule."""
    from tools.tunnelcheck.core import RULE_SUMMARIES, all_rules

    assert tunnelcheck_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    listed = [line.split()[0] for line in out.strip().splitlines()]
    assert listed == [f"TC{i:02d}" for i in range(22)]
    assert set(all_rules()) | {"TC00"} == set(RULE_SUMMARIES)

    readme = (REPO_ROOT / "README.md").read_text(encoding="utf-8")
    for rid in RULE_SUMMARIES:
        if rid == "TC00":
            continue  # framework behavior, documented in prose
        assert f"| {rid}" in readme, f"README rule table is missing {rid}"


def test_tc00_counted_in_summary_and_exit_code(tmp_path, capsys):
    """The ISSUE 11 bugfix pin: an unparseable file must show up in the
    printed summary total AND drive exit code 1 — through the default run,
    a rule filter, and the parallel path — because both are computed from
    the same violation list."""
    (tmp_path / "broken.py").write_text("def broken(:\n")
    (tmp_path / "ok.py").write_text("x = 1\n")

    rc = tunnelcheck_main([str(tmp_path)])
    err = capsys.readouterr().err
    assert rc == 1
    assert "1 violation(s)" in err

    rc = tunnelcheck_main([str(tmp_path), "--rules", "TC06"])
    err = capsys.readouterr().err
    assert rc == 1
    assert "1 violation(s)" in err


def _cli_subprocess(args):
    """Run the real CLI in a clean subprocess.  The parallel paths fork,
    and forking THIS process — pytest with JAX threads already live — is
    exactly what the fork pool must never do in production (the CLI
    process never imports jax); keep the test honest the same way."""
    import subprocess
    import sys

    return subprocess.run(
        [sys.executable, "-m", "tools.tunnelcheck", *args],
        capture_output=True, text=True, cwd=REPO_ROOT, timeout=120,
    )


def test_tc00_counted_in_summary_with_parallel_jobs(tmp_path):
    (tmp_path / "broken.py").write_text("def broken(:\n")
    (tmp_path / "ok.py").write_text("x = 1\n")
    proc = _cli_subprocess([str(tmp_path), "--jobs", "2"])
    assert proc.returncode == 1
    assert "1 violation(s)" in proc.stderr
    assert "(2 job(s))" in proc.stderr


def test_parallel_jobs_match_serial(tmp_path):
    """--jobs must be a pure speedup: identical findings (waived included),
    identical order."""
    (tmp_path / "a.py").write_text(
        "import time\n\nasync def f():\n    time.sleep(1)\n"
    )
    (tmp_path / "b.py").write_text(
        "import time\n\nasync def g():\n    time.sleep(2)  "
        "# tunnelcheck: disable=TC01  fixture\n"
    )
    (tmp_path / "c.py").write_text("def broken(:\n")
    serial = _cli_subprocess([str(tmp_path), "--show-waived"])
    parallel = _cli_subprocess([str(tmp_path), "--show-waived", "--jobs", "3"])
    assert serial.returncode == parallel.returncode == 1
    assert serial.stdout == parallel.stdout
    lines = serial.stdout.strip().splitlines()
    assert "TC01" in lines[0] and "TC00" in lines[1]  # path-sorted
    assert "[waived]" in lines[2]


def test_restrict_limits_findings_not_context(tmp_path):
    """The --changed-only substrate: findings only for the restricted
    set, while unrestricted files still feed cross-file context (the
    jit-factory below is DEFINED in an unrestricted file and must still
    poison the loop in the restricted one)."""
    factory = tmp_path / "factory.py"
    factory.write_text(
        "import jax\n\ndef make_op():\n    return jax.jit(lambda x: x)\n"
    )
    user = tmp_path / "p2p_llm_tunnel_tpu" / "engine" / "user.py"
    user.parent.mkdir(parents=True)
    user.write_text(
        "from factory import make_op\n\n"
        "def admit(requests):\n"
        "    for req in requests:\n"
        "        make_op()\n"
    )
    bad = tmp_path / "bad.py"
    bad.write_text("import time\n\nasync def f():\n    time.sleep(1)\n")

    full, _ = run_paths([tmp_path])
    assert sorted({v.rule for v in full}) == ["TC01", "TC07"]

    restricted, _ = run_paths([tmp_path], restrict={user.resolve()})
    assert [v.rule for v in restricted] == ["TC07"]
    assert restricted[0].path == user


def test_changed_only_cli_uses_git_answer(tmp_path, capsys, monkeypatch):
    """--changed-only scopes findings to what git reports; a git failure
    degrades to a full run instead of silently reporting clean."""
    import tools.tunnelcheck.__main__ as cli

    bad1 = tmp_path / "bad1.py"
    bad1.write_text("import time\n\nasync def f():\n    time.sleep(1)\n")
    bad2 = tmp_path / "bad2.py"
    bad2.write_text("import time\n\nasync def g():\n    time.sleep(1)\n")

    monkeypatch.setattr(cli, "_git_changed_files",
                        lambda root: {bad1.resolve()})
    rc = cli.main([str(tmp_path), "--changed-only"])
    captured = capsys.readouterr()
    assert rc == 1
    assert "bad1.py" in captured.out and "bad2.py" not in captured.out
    assert "1 changed of 2 file(s)" in captured.err

    monkeypatch.setattr(cli, "_git_changed_files", lambda root: None)
    rc = cli.main([str(tmp_path), "--changed-only"])
    captured = capsys.readouterr()
    assert rc == 1
    assert "bad1.py" in captured.out and "bad2.py" in captured.out


# ---------------------------------------------------------------------------
# Substrate unit tests (dataflow.py / callgraph.py)
# ---------------------------------------------------------------------------


def test_dataflow_augassign_awaiting_value_is_torn():
    """``self._x += await f()`` reads the target, suspends, then stores —
    the torn-increment shape, visible only with evaluation-order events."""
    import ast as ast_mod

    from tools.tunnelcheck.dataflow import FuncCFG, attr_reach

    tree = ast_mod.parse(
        "async def f(self):\n    self._x += await g()\n"
    )
    torn = attr_reach(FuncCFG(tree.body[0]), {"self"})
    assert [(t.obj, t.attr) for t in torn] == [("self", "_x")]


def test_dataflow_try_finally_write_sees_body_reads():
    """A finally-block write observes reads from anywhere in the try body
    (any statement may raise), so a torn RMW cannot hide in a handler."""
    import ast as ast_mod

    from tools.tunnelcheck.dataflow import FuncCFG, attr_reach

    tree = ast_mod.parse(
        "async def f(self):\n"
        "    n = self._x\n"
        "    try:\n"
        "        await g()\n"
        "    finally:\n"
        "        self._x = n + 1\n"
    )
    torn = attr_reach(FuncCFG(tree.body[0]), {"self"})
    assert [(t.obj, t.attr, t.via_local) for t in torn] == [
        ("self", "_x", "n")
    ]


def test_callgraph_transitive_callers_and_factories(tmp_path):
    from tools.tunnelcheck.callgraph import CallGraph
    from tools.tunnelcheck.core import load_source

    f = tmp_path / "mod.py"
    f.write_text(
        "import jax\n\n"
        "def factory():\n    return jax.jit(lambda x: x)\n\n"
        "def middle():\n    return factory()\n\n"
        "def outer():\n    return middle()\n\n"
        "def unrelated():\n    return 1\n"
    )
    sf, err = load_source(f)
    assert err is None
    graph = CallGraph([sf])
    assert graph.functions_calling("jax.jit") == {"factory"}
    closure = graph.transitive_callers(
        lambda n: "jax.jit" in n.dotted_calls, within=f
    )
    assert closure == {"factory", "middle", "outer"}
    assert graph.resolve("outer") is not None
    assert graph.resolve("nope") is None


def test_callgraph_indexes_defs_in_nested_compounds(tmp_path):
    """Coverage regression pin: defs inside except handlers, doubly-nested
    ifs, and loops inside try must be indexed exactly like the full-
    recursion walkers the call graph replaced — a def the graph cannot
    see is a def TC02/TC03/TC07/TC09 silently stop checking."""
    import ast as ast_mod

    from tools.tunnelcheck.callgraph import CallGraph
    from tools.tunnelcheck.core import load_source
    from tools.tunnelcheck.dataflow import iter_functions

    f = tmp_path / "mod.py"
    f.write_text(
        "try:\n"
        "    import fast\n"
        "except ImportError:\n"
        "    def fallback(x):\n"
        "        return x\n"
        "\n"
        "if True:\n"
        "    if True:\n"
        "        def doubly_nested():\n"
        "            pass\n"
        "\n"
        "class C:\n"
        "    try:\n"
        "        def meth(self):\n"
        "            pass\n"
        "    except Exception:\n"
        "        pass\n"
        "\n"
        "for _ in range(1):\n"
        "    def in_loop():\n"
        "        pass\n"
        "\n"
        "match 1:\n"
        "    case 1:\n"
        "        def in_match():\n"
        "            pass\n"
        "    case _:\n"
        "        pass\n"
    )
    sf, err = load_source(f)
    assert err is None
    graph = CallGraph([sf])
    indexed = {id(n.node) for n in graph.by_path[f]}
    for fn, _cls in iter_functions(sf.tree):
        assert id(fn) in indexed, f"call graph missed `{fn.name}`"
    meth = [n for n in graph.by_path[f] if n.name == "meth"]
    assert meth and meth[0].info.is_method  # class context survives nesting


# ---------------------------------------------------------------------------
# TC19 — packed-KV writes only through the byte-aligned helpers
# ---------------------------------------------------------------------------

KV_FIXTURE = "p2p_llm_tunnel_tpu/models/fixture_kv.py"


def test_tc19_direct_packed_write_flags(tmp_path):
    """The incident shape: a pack_int4 result fed straight into a plane
    write at a call site — arbitrary-parity starts clobber the shared
    edge bytes (the bug the spec_ngram x kv-int4 fence hid)."""
    active, _ = check(
        tmp_path,
        """
        def scatter(plane, rows, bpos, vals):
            return plane.at[0, rows, bpos].set(pack_int4(vals, axis=1))
        """,
        filename=KV_FIXTURE,
        rules=["TC19"],
    )
    assert rules_of(active) == ["TC19"]
    assert "splice_packed_rows" in active[0].message


def test_tc19_taint_flows_through_locals(tmp_path):
    active, _ = check(
        tmp_path,
        """
        def scatter(plane, rows, vals):
            packed = pack_int4(vals, axis=1)
            staged = packed
            return plane.at[0, rows].set(staged)
        """,
        filename=KV_FIXTURE,
        rules=["TC19"],
    )
    assert rules_of(active) == ["TC19"]


def test_tc19_hand_rolled_nibble_merge_flags(tmp_path):
    """The pre-helper RMW idiom evades the packer taint by never calling
    pack_int4 — the (hi << 4) | lo shape is flagged on its own."""
    active, _ = check(
        tmp_path,
        """
        def append(plane, idx, slots, bidx, lo, hi):
            return plane.at[idx, slots, bidx].set(
                ((hi << 4) | (lo & 0x0F)).astype(jnp.int8)
            )
        """,
        filename=KV_FIXTURE,
        rules=["TC19"],
    )
    assert rules_of(active) == ["TC19"]
    assert "nibble merge" in active[0].message


def test_tc19_helper_bodies_are_sanctioned(tmp_path):
    """The four audited commit points may (must) do exactly what every
    other function is banned from doing."""
    active, _ = check(
        tmp_path,
        """
        def write_packed_chunk(plane, idx, rows, bpos, vals):
            return plane.at[idx, rows, bpos].set(pack_int4(vals, axis=1))

        def append_packed_token(plane, idx, slots, positions, vals):
            old = plane[idx, slots, positions // 2]
            lo = jnp.where(True, vals, old) & 0x0F
            hi = jnp.where(True, old >> 4, vals)
            return plane.at[idx, slots, positions // 2].set(
                (jnp.left_shift(hi, 4) | lo).astype(jnp.int8)
            )
        """,
        filename=KV_FIXTURE,
        rules=["TC19"],
    )
    assert active == []


def test_tc19_unpacked_writes_and_helper_calls_clean(tmp_path):
    """Raw (unpacked) values into a plane, and UNPACKED values handed to
    an audited helper, are both fine — the helper packs internally."""
    active, _ = check(
        tmp_path,
        """
        def prefill(cache, slots, k, kq):
            cache = cache.at[:, slots].set(k)
            return write_packed_prefix(cache, slots, kq)

        def verify(cache, idx, slots, starts, kq):
            return splice_packed_rows(cache, idx, slots, starts, kq)
        """,
        filename=KV_FIXTURE,
        rules=["TC19"],
    )
    assert active == []


def test_tc19_out_of_scope_tree_ignored(tmp_path):
    active, _ = check(
        tmp_path,
        """
        def scatter(plane, vals):
            return plane.at[0].set(pack_int4(vals, axis=1))
        """,
        filename="experiments/scratch.py",
        rules=["TC19"],
    )
    assert active == []


def test_tc19_waiver_parses(tmp_path):
    active, waived = check(
        tmp_path,
        """
        def scatter(plane, vals):
            return plane.at[0].set(pack_int4(vals, axis=1))  # tunnelcheck: disable=TC19  scale plane, not a packed token plane
        """,
        filename=KV_FIXTURE,
        rules=["TC19"],
    )
    assert active == []
    assert rules_of(waived) == ["TC19"]


def test_tc19_kv_write_paths_self_run_clean():
    """The real packed-write paths are TC19-clean WITHOUT waivers: since
    ISSUE 17 every XLA-path packed write in quant/transformer/engine
    routes through the four byte-aligned helpers."""
    base = REPO_ROOT / "p2p_llm_tunnel_tpu"
    files = [base / "models" / "quant.py",
             base / "models" / "transformer.py",
             base / "engine" / "engine.py",
             base / "engine" / "prefix_cache.py"]
    active, waived = run_paths(files, rules=["TC19"])
    assert active == []
    assert rules_of(waived) == []


# ---------------------------------------------------------------------------
# Interprocedural summary engine (ISSUE 18 tentpole) — unit tests against
# dataflow.interproc_taint directly: transfer functions, fixpoint
# termination, and the depth bound.
# ---------------------------------------------------------------------------


def _interproc_engine(tmp_path, code, *, on_sink_calls=("sink",),
                      max_depth=4):
    """Build an InterprocTaint over one fixture module under a toy policy:
    ``taint_src()`` is THE source, ``clean()`` THE sanitizer, ``sink()``'s
    first argument THE sink."""
    import ast as _ast

    from tools.tunnelcheck.callgraph import CallGraph
    from tools.tunnelcheck.core import load_source
    from tools.tunnelcheck.dataflow import (
        TaintPolicy,
        call_name,
        interproc_taint,
    )

    f = tmp_path / "mod.py"
    f.write_text(textwrap.dedent(code))
    sf, err = load_source(f)
    assert err is None

    def is_source(expr):
        return isinstance(expr, __import__("ast").Call) and \
            call_name(expr) == "taint_src"

    def sink_args(call):
        if call_name(call) in on_sink_calls and call.args:
            return [(call.args[0], f"the `{call_name(call)}` sink")]
        return []

    policy = TaintPolicy(
        is_source=is_source,
        sanitizers=frozenset({"clean"}),
        seed_params=frozenset(),
        sink_args=sink_args,
        sink_assign=lambda node: [],
    )
    graph = CallGraph([sf])
    return interproc_taint(graph, policy, max_depth=max_depth), graph


def _summary(engine, graph, name):
    node = graph.by_name[name][0].node
    s = engine.summary_for(node)
    assert s is not None
    return s


def test_interproc_summary_param_to_return_transfer(tmp_path):
    engine, graph = _interproc_engine(
        tmp_path,
        """
        def ident(x):
            return x

        def fresh(x):
            return 1

        def srcfn():
            return taint_src()

        def laundered(x):
            return clean(x)
        """,
    )
    from tools.tunnelcheck.dataflow import SRC

    assert _summary(engine, graph, "ident").ret == {"x"}
    assert _summary(engine, graph, "fresh").ret == set()
    assert _summary(engine, graph, "srcfn").ret == {SRC}
    # The sanitizer's RESULT is clean whatever it read: the registered-
    # sanitizer contract, applied at the summary level.
    assert _summary(engine, graph, "laundered").ret == set()


def test_interproc_sink_params_and_cross_function_report(tmp_path):
    engine, graph = _interproc_engine(
        tmp_path,
        """
        def stamp(v):
            sink(v)

        def top():
            stamp(taint_src())
        """,
    )
    s = _summary(engine, graph, "stamp")
    assert set(s.sink_params) == {"v"}
    hits = []
    engine.analyze(graph.by_name["top"][0].node,
                   on_sink=lambda node, d: hits.append((node.lineno, d)))
    assert len(hits) == 1
    # The report lands at top's CALL to stamp and names the chain.
    assert "via `stamp()`" in hits[0][1]


def test_interproc_fixpoint_terminates_on_mutual_recursion(tmp_path):
    engine, graph = _interproc_engine(
        tmp_path,
        """
        def ping(x):
            return pong(x)

        def pong(x):
            if x:
                return ping(x)
            return x

        def forever_a(x):
            return forever_b(x)

        def forever_b(x):
            return forever_a(x)
        """,
    )
    # Monotone-from-empty: summaries only grow, so the iteration stops at
    # the fixpoint within the depth bound instead of chasing the cycle.
    assert engine.rounds <= engine.max_depth
    # A cycle with NO base case never returns its argument — the empty
    # summary is the semantically correct answer, not a missed fact.
    assert _summary(engine, graph, "forever_a").ret == set()
    # A cycle WITH a base case transfers its parameter through both hops.
    assert _summary(engine, graph, "ping").ret == {"x"}
    assert _summary(engine, graph, "pong").ret == {"x"}


def test_interproc_depth_bound_caps_chain_length(tmp_path):
    chain = """
        def h5(x):
            return x

        def h4(x):
            return h5(x)

        def h3(x):
            return h4(x)

        def h2(x):
            return h3(x)

        def h1(x):
            return h2(x)
        """
    shallow, graph_s = _interproc_engine(tmp_path, chain, max_depth=2)
    assert _summary(shallow, graph_s, "h1").ret == set()
    deep, graph_d = _interproc_engine(tmp_path, chain, max_depth=8)
    assert _summary(deep, graph_d, "h1").ret == {"x"}
    # 5 hops resolve in ~5 rounds + 1 no-change round, never the full 8.
    assert deep.rounds <= 7


# ---------------------------------------------------------------------------
# TC20 — extracted page bytes must pass verify_page_pin before any
# tunnel send / tier write / splice (interprocedural)
# ---------------------------------------------------------------------------


def test_tc20_extracted_page_sent_flags(tmp_path):
    active, _ = check(
        tmp_path,
        """
        def evict(self, idx):
            page = self._page_out_op(self._pool, idx)
            self._link.send_bytes(page)
        """,
        filename=SPILL_FIXTURE,
        rules=["TC20"],
    )
    assert rules_of(active) == ["TC20"]
    assert "verify_page_pin" in active[0].message


def test_tc20_cross_function_laundering_flags_at_call_site(tmp_path):
    """The boundary-crossing shape TC18 cannot see: extraction in one
    function, the send hidden inside a helper."""
    active, _ = check(
        tmp_path,
        """
        class Tier:
            def ship(self, link, page):
                link.send_bytes(page)

            def evict(self, link, idx):
                page = self._page_out_op(self._pool, idx)
                self.ship(link, page)
        """,
        filename=SPILL_FIXTURE,
        rules=["TC20"],
    )
    assert rules_of(active) == ["TC20"]
    assert "via `ship()`" in active[0].message
    assert "self.ship(link, page)" in (tmp_path / SPILL_FIXTURE).read_text(
    ).splitlines()[active[0].line - 1]


def test_tc20_cross_function_sanitizer_clears(tmp_path):
    """verify_page_pin inside a helper launders for every caller: the
    summary records the cleared return, not the raw parameter."""
    active, _ = check(
        tmp_path,
        """
        class Tier:
            def pin(self, page):
                return verify_page_pin(page, self._meta, self._want)

            def evict(self, link, idx):
                page = self._page_out_op(self._pool, idx)
                link.send_bytes(self.pin(page))
        """,
        filename=SPILL_FIXTURE,
        rules=["TC20"],
    )
    assert active == []


def test_tc20_call_graph_cycle_terminates_and_flags(tmp_path):
    active, _ = check(
        tmp_path,
        """
        class Tier:
            def hop_a(self, link, page, n):
                if n:
                    self.hop_b(link, page, n - 1)
                link.send_bytes(page)

            def hop_b(self, link, page, n):
                self.hop_a(link, page, n)

            def evict(self, link, idx):
                page = self._page_out_op(self._pool, idx)
                self.hop_b(link, page, 2)
        """,
        filename=SPILL_FIXTURE,
        rules=["TC20"],
    )
    assert rules_of(active) == ["TC20"]


def test_tc20_payload_receiver_heuristic(tmp_path):
    """``spill_page.payload`` is page bytes; ``msg.payload`` is frame
    plumbing — only receivers named like pages seed the taint, so the
    signaling/frames layer's ubiquitous payload fields stay silent."""
    active, _ = check(
        tmp_path,
        """
        def drain(self, spill_page, key):
            self._index.note_spilled(key, spill_page.payload)

        def pump(self, msg):
            self._link.send_bytes(msg.payload)
        """,
        filename=SPILL_FIXTURE,
        rules=["TC20"],
    )
    assert rules_of(active) == ["TC20"]
    assert active[0].message.count("tier write") == 1


def test_tc20_waiver_and_out_of_scope(tmp_path):
    code = """
        def evict(self, idx):
            page = self._page_out_op(self._pool, idx)
            self._link.send_bytes(page)  # tunnelcheck: disable=TC20  loopback self-test: bytes re-enter this process through the same pins
        """
    active, waived = check(tmp_path, code, filename=SPILL_FIXTURE,
                           rules=["TC20"])
    assert active == []
    assert rules_of(waived) == ["TC20"]
    active, _ = check(tmp_path, code, filename="elsewhere/spill.py",
                      rules=["TC20"])
    assert active == []


def test_tc20_meta_fixture_stripped_real_chain_flags():
    """Acceptance meta-fixture: take the ENGINE'S real page-in chain
    (_spill_copy_in), strip the verify_page_pin reassignment, and TC20
    must fire — proof the rule guards the production shape, not a toy.
    The unstripped copy is the control: clean with zero waivers."""
    import ast as _ast
    import tempfile

    src = (REPO_ROOT / "p2p_llm_tunnel_tpu" / "engine" / "engine.py"
           ).read_text(encoding="utf-8")
    fn = next(
        n for n in _ast.walk(_ast.parse(src))
        if isinstance(n, _ast.FunctionDef) and n.name == "_spill_copy_in"
    )

    with tempfile.TemporaryDirectory() as td:
        active, _ = check(Path(td), _ast.unparse(fn),
                          filename=SPILL_FIXTURE, rules=["TC20"])
        assert active == [], "the real chain must be clean as shipped"

    class StripPin(_ast.NodeTransformer):
        def visit_Assign(self, node):
            if (isinstance(node.value, _ast.Call)
                    and isinstance(node.value.func, _ast.Name)
                    and node.value.func.id == "verify_page_pin"):
                return None
            return node

    stripped = _ast.fix_missing_locations(StripPin().visit(fn))
    with tempfile.TemporaryDirectory() as td:
        active, _ = check(Path(td), _ast.unparse(stripped),
                          filename=SPILL_FIXTURE, rules=["TC20"])
        assert rules_of(active) == ["TC20"]
        assert "splice" in active[0].message


def test_tc20_registries_match_runtime():
    """Runtime agreement: the sanitizer TC20 credits and the extraction /
    tier-write names it watches are the REAL prefix_cache symbols — the
    static model cannot drift from what the runtime enforces."""
    from p2p_llm_tunnel_tpu.engine import prefix_cache
    from tools.tunnelcheck import rules_tierpin as rt

    for name in rt.SANITIZERS:
        assert callable(getattr(prefix_cache, name)), name
    assert hasattr(prefix_cache.PrefixIndex, "export_state")
    for name in rt.TIER_WRITE_CALLS:
        assert callable(getattr(prefix_cache.PrefixIndex, name)), name


def test_tc20_send_registry_covers_kv_pages_wire_path():
    """ISSUE 20 agreement: the KV_PAGES transfer framer the runtime uses
    to put pool bytes on the wire is a registered TC20 send sink — an
    unpinned export cannot reach a transfer frame even when the actual
    ``channel.send`` of the encoded frame lives in another function —
    and the registered name IS the runtime symbol."""
    from p2p_llm_tunnel_tpu.protocol import frames
    from tools.tunnelcheck import rules_tierpin as rt

    assert "kv_pages_chunk" in rt.SEND_CALLS
    assert callable(getattr(frames.TunnelMessage, "kv_pages_chunk"))
    for mt in ("KV_PAGES_HDR", "KV_PAGES_CHUNK", "KV_PAGES_END",
               "KV_PAGES_ACK"):
        assert hasattr(frames.MessageType, mt)


def test_tc20_unpinned_bytes_into_kv_pages_chunk_flag(tmp_path):
    """Pool bytes that skip verify_page_pin must not enter a KV_PAGES
    frame: the framer itself is the sink, so the violation lands in the
    function that builds the frame, not wherever the send happens."""
    active, _ = check(
        tmp_path,
        """
        from p2p_llm_tunnel_tpu.protocol.frames import TunnelMessage

        def ship(pool, op, sid):
            raw = op.page_out(pool, 3)
            return TunnelMessage.kv_pages_chunk(sid, raw)
        """,
        filename=SPILL_FIXTURE,
        rules=["TC20"],
    )
    assert rules_of(active) == ["TC20"]
    assert "send" in active[0].message


def test_tc20_engine_and_prefix_cache_self_run():
    """The shipped extraction->boundary paths pass TC20 with only the
    documented warmup waiver (engine.py's compile round-trip)."""
    eng = REPO_ROOT / "p2p_llm_tunnel_tpu" / "engine" / "engine.py"
    pfx = REPO_ROOT / "p2p_llm_tunnel_tpu" / "engine" / "prefix_cache.py"
    active, waived = run_paths([eng, pfx], rules=["TC20"])
    assert active == []
    assert rules_of(waived) == ["TC20"]


# ---------------------------------------------------------------------------
# TC21 — interprocedural header taint (TC14 across function boundaries)
# ---------------------------------------------------------------------------

TAINT21_FIXTURE = "p2p_llm_tunnel_tpu/endpoints/fixture_taint21.py"


def test_tc21_extraction_helper_flags_at_call_site(tmp_path):
    """The pre-PR-7 minting hole one call deep: a helper RETURNS the raw
    header value, so TC14's flat lattice sees a clean call result."""
    active, _ = check(
        tmp_path,
        """
        def grab(req):
            return req.headers.get("x-tunnel-tenant", "")

        def admit(req, sched):
            sched.tenant_begin(grab(req))
        """,
        filename=TAINT21_FIXTURE,
        rules=["TC14", "TC21"],
    )
    assert rules_of(active) == ["TC21"]
    assert "helper" in active[0].message


def test_tc21_stamping_helper_flags_at_call_site(tmp_path):
    """The dual shape: the SINK hides inside the helper."""
    active, _ = check(
        tmp_path,
        """
        def stamp(kw, raw):
            kw["tenant"] = raw

        def admit(req, kw):
            stamp(kw, req.headers.get("x-tunnel-tenant", ""))
        """,
        filename=TAINT21_FIXTURE,
        rules=["TC14", "TC21"],
    )
    assert rules_of(active) == ["TC21"]


def test_tc21_sanitized_helper_is_clean(tmp_path):
    active, _ = check(
        tmp_path,
        """
        def grab(req):
            return parse_tenant(req.headers.get("x-tunnel-tenant", ""))

        def admit(req, sched):
            sched.tenant_begin(grab(req))
        """,
        filename=TAINT21_FIXTURE,
        rules=["TC14", "TC21"],
    )
    assert active == []


def test_tc21_does_not_duplicate_tc14_findings(tmp_path):
    """Same-line flows belong to TC14; TC21 reporting them too would
    double every waiver in the tree."""
    active, _ = check(
        tmp_path,
        """
        def admit(req, sched):
            sched.tenant_begin(req.headers.get("x-tunnel-tenant", ""))
        """,
        filename=TAINT21_FIXTURE,
        rules=["TC14", "TC21"],
    )
    assert rules_of(active) == ["TC14"]


def test_tc21_waiver_and_cycle(tmp_path):
    active, waived = check(
        tmp_path,
        """
        def bounce(req, sched, n):
            if n:
                relay(req, sched, n - 1)
            return req.headers.get("x-t", "")

        def relay(req, sched, n):
            sched.tenant_begin(bounce(req, sched, n))  # tunnelcheck: disable=TC21  herd-test harness: headers are fixture constants
        """,
        filename=TAINT21_FIXTURE,
        rules=["TC14", "TC21"],
    )
    assert active == []
    assert rules_of(waived) == ["TC21"]


def test_tc21_package_self_run_is_clean():
    pkg = REPO_ROOT / "p2p_llm_tunnel_tpu"
    active, _ = run_paths([pkg], rules=["TC21"])
    assert active == []


# ---------------------------------------------------------------------------
# Per-file result cache (ISSUE 18 satellite)
# ---------------------------------------------------------------------------


def test_cache_cold_then_warm_same_results(tmp_path):
    f = tmp_path / "snippet.py"
    f.write_text(textwrap.dedent(
        """
        import time

        async def handler():
            time.sleep(1)
            time.sleep(2)  # tunnelcheck: disable=TC01  fixture
        """
    ))
    cache = tmp_path / "cache"
    stats_cold: dict = {}
    a_cold, w_cold = run_paths([f], rules=["TC01"], stats=stats_cold,
                               cache_dir=cache)
    assert stats_cold["cache_misses"] == 1
    assert stats_cold["cache_hits"] == 0
    stats_warm: dict = {}
    a_warm, w_warm = run_paths([f], rules=["TC01"], stats=stats_warm,
                               cache_dir=cache)
    assert stats_warm["cache_hits"] == 1
    assert stats_warm["cache_misses"] == 0
    # The warm partition is IDENTICAL, waived findings included.
    assert [(v.rule, v.line) for v in a_warm] == \
        [(v.rule, v.line) for v in a_cold]
    assert [(v.rule, v.line) for v in w_warm] == \
        [(v.rule, v.line) for v in w_cold]


def test_cache_invalidated_by_any_edit(tmp_path):
    """The key commits to the WHOLE tree digest: interprocedural rules
    make per-file isolation unsound, so editing one file must invalidate
    every entry — honest, not clever."""
    a = tmp_path / "a.py"
    b = tmp_path / "b.py"
    a.write_text("x = 1\n")
    b.write_text("y = 2\n")
    cache = tmp_path / "cache"
    run_paths([a, b], rules=["TC01"], stats={}, cache_dir=cache)
    stats: dict = {}
    run_paths([a, b], rules=["TC01"], stats=stats, cache_dir=cache)
    assert stats["cache_hits"] == 2
    b.write_text("y = 3\n")
    stats = {}
    run_paths([a, b], rules=["TC01"], stats=stats, cache_dir=cache)
    assert stats["cache_hits"] == 0
    assert stats["cache_misses"] == 2


def test_cache_keyed_on_selected_rules(tmp_path):
    f = tmp_path / "snippet.py"
    f.write_text("import time\n\nasync def h():\n    time.sleep(1)\n")
    cache = tmp_path / "cache"
    run_paths([f], rules=["TC01"], stats={}, cache_dir=cache)
    stats: dict = {}
    active, _ = run_paths([f], rules=["TC05"], stats=stats, cache_dir=cache)
    assert stats["cache_hits"] == 0  # different rule set, different key
    assert active == []


# ---------------------------------------------------------------------------
# Waiver audit (ISSUE 18 satellite)
# ---------------------------------------------------------------------------


def test_waiver_audit_flags_stale_and_keeps_live(tmp_path):
    f = tmp_path / "snippet.py"
    f.write_text(textwrap.dedent(
        """
        import time

        async def handler():
            time.sleep(1)  # tunnelcheck: disable=TC01  live: suppresses a real finding
            x = 1  # tunnelcheck: disable=TC01  stale: nothing fires here
        """
    ))
    audit: list = []
    active, waived = run_paths([f], rules=["TC01"], waiver_audit=audit)
    assert active == []
    assert rules_of(waived) == ["TC01"]
    assert len(audit) == 1
    path, line, msg = audit[0]
    assert line == 6 and "stale waiver" in msg and "TC01" in msg


def test_waiver_audit_unknown_rule_id_always_reported(tmp_path):
    f = tmp_path / "snippet.py"
    f.write_text("x = 1  # tunnelcheck: disable=TC99  typo'd id\n")
    audit: list = []
    run_paths([f], rules=["TC01"], waiver_audit=audit)
    assert len(audit) == 1
    assert "unknown rule" in audit[0][2] and "TC99" in audit[0][2]


def test_waiver_audit_stale_file_waiver(tmp_path):
    f = tmp_path / "snippet.py"
    f.write_text("# tunnelcheck: disable-file=TC01\nx = 1\n")
    audit: list = []
    run_paths([f], rules=["TC01"], waiver_audit=audit)
    assert len(audit) == 1
    assert "file waiver" in audit[0][2] and audit[0][1] == 1


def test_waiver_audit_skips_unselected_rules(tmp_path):
    """A subset run cannot judge a waiver for a rule it didn't execute —
    silence, not a false stale report."""
    f = tmp_path / "snippet.py"
    f.write_text("x = 1  # tunnelcheck: disable=TC05  judged only when TC05 runs\n")
    audit: list = []
    run_paths([f], rules=["TC01"], waiver_audit=audit)
    assert audit == []
    audit = []
    run_paths([f], rules=["TC05"], waiver_audit=audit)
    assert len(audit) == 1


def test_waiver_audit_shipped_tree_has_no_stale_waivers():
    """Waiver hygiene as an invariant: every `# tunnelcheck: disable=`
    comment in the tree suppresses a finding that actually fires (the
    16 dead comments found when the audit landed are gone)."""
    audit: list = []
    run_paths(
        [REPO_ROOT / "p2p_llm_tunnel_tpu", REPO_ROOT / "scripts",
         REPO_ROOT / "tests", REPO_ROOT / "bench.py",
         REPO_ROOT / "__graft_entry__.py"],
        waiver_audit=audit,
    )
    assert audit == [], f"stale waivers: {audit}"


# ---------------------------------------------------------------------------
# CLI: wall-time budget + cache/audit plumbing (ISSUE 18 satellite)
# ---------------------------------------------------------------------------


def test_cli_budget_gate(tmp_path, capsys):
    f = tmp_path / "clean.py"
    f.write_text("x = 1\n")
    assert tunnelcheck_main([str(f), "--budget-s", "600"]) == 0
    capsys.readouterr()
    assert tunnelcheck_main([str(f), "--budget-s", "0"]) == 1
    err = capsys.readouterr().err
    assert "exceeded" in err and "budget" in err


def test_cli_cache_and_audit_summary(tmp_path, capsys):
    f = tmp_path / "snippet.py"
    f.write_text("x = 1  # tunnelcheck: disable=TC99  typo\n")
    cache = tmp_path / "cache"
    args = [str(f), "--cache", str(cache), "--waiver-audit"]
    assert tunnelcheck_main(args) == 0
    err = capsys.readouterr().err
    assert "0 hit(s) 1 miss(es)" in err
    assert "1 stale waiver(s)" in err
    assert "waiver-audit: waiver names unknown rule `TC99`" in err
    assert tunnelcheck_main(args) == 0
    err = capsys.readouterr().err
    assert "1 hit(s) 0 miss(es)" in err
    # The audit still reports from the CACHED entry's re-parse.
    assert "1 stale waiver(s)" in err
