"""Ulysses all-to-all sequence parallelism vs the dense oracle (CPU mesh).

The second SP strategy of SURVEY §5 (ring attention is the first); pinned
to ops/attention.causal_attention over every knob ring cannot do: pad
masks and sliding windows survive because each device attends over the
full sequence for its head shard.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from p2p_llm_tunnel_tpu.models.config import get_config
from p2p_llm_tunnel_tpu.models.transformer import init_params, prefill
from p2p_llm_tunnel_tpu.ops.attention import causal_attention
from p2p_llm_tunnel_tpu.ops.ulysses_attention import make_ulysses_attention
from p2p_llm_tunnel_tpu.parallel import make_mesh

# Compile-heavy (JAX jit of engine/model programs): excluded from
# `make test-fast` (VERDICT r4 item 8).
pytestmark = pytest.mark.slow


def _qkv(b=2, t=16, h=4, kh=2, d=8, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(ks[0], (b, t, h, d), jnp.float32)
    k = jax.random.normal(ks[1], (b, t, kh, d), jnp.float32)
    v = jax.random.normal(ks[2], (b, t, kh, d), jnp.float32)
    return q, k, v


@pytest.mark.parametrize("sp", [2])
def test_matches_dense_oracle(cpu_devices, sp):
    q, k, v = _qkv()
    valid = jnp.ones((2, 16), bool)
    mesh = make_mesh(sp=sp, devices=cpu_devices[:sp])
    ulysses = make_ulysses_attention(mesh, "sp")
    want = causal_attention(q, k, v, valid)
    got = jax.jit(lambda *a: ulysses(*a))(q, k, v, valid)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_pad_mask_and_window_supported(cpu_devices):
    """The two capabilities ring attention lacks: ragged pad masks and
    sliding windows both match the dense oracle."""
    q, k, v = _qkv(seed=1)
    valid = jnp.arange(16)[None, :] < jnp.array([[10], [16]])
    mesh = make_mesh(sp=2, devices=cpu_devices[:2])
    ulysses = make_ulysses_attention(mesh, "sp")
    for window in (None, 4):
        want = causal_attention(q, k, v, valid, window=window)
        got = jax.jit(
            lambda q_, k_, v_, va: ulysses(q_, k_, v_, va, window=window)
        )(q, k, v, valid)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5,
                                   err_msg=f"window={window}")


def test_rejects_indivisible_heads(cpu_devices):
    q, k, v = _qkv(h=4, kh=2)
    mesh = make_mesh(sp=4, devices=cpu_devices[:4])
    ulysses = make_ulysses_attention(mesh, "sp")
    with pytest.raises(ValueError, match="divisible"):
        ulysses(q, k, v, jnp.ones((2, 16), bool))


def test_full_model_prefill_ulysses(cpu_devices):
    """End-to-end prefill with sp_mode='ulysses' matches the unsharded
    forward — including on the WINDOWED gemma-style config that the ring
    path must reject."""
    for preset in ("tiny", "tiny-gemma"):
        cfg = get_config(preset, sp_mode="ulysses")
        params = init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0,
                                    cfg.vocab_size)
        valid = jnp.arange(16)[None, :] < jnp.array([[12], [16]])
        want, _, _ = prefill(cfg, params, tokens, valid)
        mesh = make_mesh(sp=2, devices=cpu_devices[:2])
        got, _, _ = jax.jit(
            lambda p, tok, va: prefill(cfg, p, tok, va, mesh=mesh)
        )(params, tokens, valid)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4,
            err_msg=f"ulysses prefill diverges on {preset}",
        )


def test_ring_still_rejects_windows(cpu_devices):
    cfg = get_config("tiny-gemma")  # windowed, sp_mode defaults to ring
    params = init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    mesh = make_mesh(sp=2, devices=cpu_devices[:2])
    with pytest.raises(NotImplementedError, match="ring"):
        prefill(cfg, params, jnp.zeros((2, 16), jnp.int32),
                jnp.ones((2, 16), bool), mesh=mesh)


def test_engine_sp_ulysses_generates(cpu_devices):
    import asyncio

    from p2p_llm_tunnel_tpu.engine.engine import EngineConfig, InferenceEngine

    eng = InferenceEngine(
        engine_cfg=EngineConfig(model="tiny", num_slots=2, max_seq=64,
                                dtype="float32", decode_steps=2,
                                sp=2, sp_mode="ulysses")
    )
    assert eng.mcfg.sp_mode == "ulysses"

    async def main():
        await eng.start()
        toks = []
        async for ev in eng.generate(list(b"ulysses"), max_new_tokens=5,
                                     stop_ids=()):
            toks.append(ev.token_id)
        await eng.stop()
        return toks

    toks = asyncio.run(asyncio.wait_for(main(), 120))
    assert len(toks) == 5


def test_ulysses_composes_with_tp(cpu_devices):
    """tp×sp mesh: heads shard on tp outside the all_to_all; numerics still
    match the dense oracle (each tp shard swaps only its own head slice)."""
    q, k, v = _qkv(h=4, kh=4, t=16)
    valid = jnp.ones((2, 16), bool)
    mesh = make_mesh(tp=2, sp=2, devices=cpu_devices[:4])
    ulysses = make_ulysses_attention(mesh, "sp", head_axis="tp")
    want = causal_attention(q, k, v, valid)
    got = jax.jit(lambda *a: ulysses(*a))(q, k, v, valid)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_explicit_model_cfg_sp_mode_not_reverted(cpu_devices):
    """An explicitly-ulysses model_cfg must survive a default EngineConfig
    (the engine only promotes NON-default sp_mode choices)."""
    from p2p_llm_tunnel_tpu.engine.engine import EngineConfig, InferenceEngine

    cfg = get_config("tiny", sp_mode="ulysses")
    eng = InferenceEngine(
        model_cfg=cfg,
        engine_cfg=EngineConfig(model="tiny", num_slots=2, max_seq=64,
                                dtype="float32", sp=2),
    )
    assert eng.mcfg.sp_mode == "ulysses"
