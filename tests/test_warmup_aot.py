"""Parallel AOT warmup (engine._warm_aot_parallel) equivalence tests.

The warmup's phase A AOT-compiles every warm program from concurrent
threads via ``jit.lower(...).compile()`` and relies on the persistent
compilation cache to hand those executables back to the serial execute
pass (and to live dispatch).  That only works if the AOT-lowered programs
hash IDENTICALLY to the ones live dispatch builds — any aval drift
(shape/dtype/static-arg mismatch in _decode_warm_args/_chunk_warm_args)
silently doubles compile work on the serving path, which on the
tunneled-TPU deployment costs a whole chip window (PERF.md r5).

The hash-identity proof: warm up engine A with the AOT phase ON, snapshot
the persistent-cache file set, then warm up an identically-configured
engine B with the AOT phase OFF — B's serial compiles must ALL hit the
persistent cache, i.e. add zero new files.
"""

import asyncio
import os

import pytest

import jax

from p2p_llm_tunnel_tpu.engine.engine import EngineConfig, InferenceEngine
from p2p_llm_tunnel_tpu.engine.tokenizer import ByteTokenizer

pytestmark = pytest.mark.slow

ECFG = dict(
    model="tiny", num_slots=4, max_seq=256, dtype="float32", seed=0,
    decode_steps=4, decode_steps_eager=2, prefill_rows=2,
    prefix_cache=True,
)


async def _collect(engine, prompt, max_new=8):
    out = []
    async for ev in engine.generate(prompt, max_new_tokens=max_new,
                                    stop_ids=()):
        out.append(ev.token_id)
    return out


def _cache_files(path):
    return {f for f in os.listdir(path)}


@pytest.fixture()
def persistent_cache(tmp_path, monkeypatch):
    old_dir = jax.config.jax_compilation_cache_dir
    old_min = jax.config.jax_persistent_cache_min_compile_time_secs
    jax.config.update("jax_compilation_cache_dir", str(tmp_path))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    yield str(tmp_path)
    jax.config.update("jax_compilation_cache_dir", old_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", old_min)


PROMPT = ByteTokenizer().encode("hello aot")


def test_aot_programs_hash_identical_to_dispatch(persistent_cache,
                                                 monkeypatch):
    monkeypatch.setenv("TUNNEL_WARMUP_VIEW_CAP", "100")
    # Covers the prefill-hint path: the live generate below prefills
    # len(PROMPT) tokens, so its bucket must be AOT-warmed too.
    monkeypatch.setenv("TUNNEL_WARMUP_PREFILL_TOKENS", str(len(PROMPT)))

    marks = {}

    async def run(par):
        monkeypatch.setenv("TUNNEL_WARMUP_PAR", par)
        eng = InferenceEngine(
            engine_cfg=EngineConfig(**ECFG), tokenizer=ByteTokenizer()
        )
        await eng.start()
        await eng.warmup()
        marks[f"warm{par}"] = _cache_files(persistent_cache)
        toks = await _collect(eng, PROMPT)
        await eng.stop()
        return toks

    toks_a = asyncio.run(run("2"))
    files_after_a = _cache_files(persistent_cache)
    assert marks["warm2"], "AOT warmup wrote nothing to the cache"
    # Live dispatch (prefill wave + decode bursts + prefix insert) must
    # hit only pre-warmed programs — any new cache file means a warm-args
    # builder drifted from its live call and a fresh compile landed on
    # the serving path.
    live_new = files_after_a - marks["warm2"]
    assert not live_new, (
        f"live dispatch compiled {len(live_new)} programs warmup missed"
    )

    toks_b = asyncio.run(run("0"))
    new = _cache_files(persistent_cache) - files_after_a
    assert not new, (
        f"serial warmup compiled {len(new)} programs the AOT phase "
        f"missed or mis-hashed"
    )
    assert toks_a == toks_b


def test_warmup_view_cap():
    """Cap arithmetic mirrors _kv_view_bucket's pipelining pad."""
    eng = InferenceEngine(
        engine_cfg=EngineConfig(**{**ECFG, "prefix_cache": False}),
        tokenizer=ByteTokenizer(),
    )
    # max_seq 256 -> full bucket list [128, 256].
    assert eng._view_buckets() == [128, 256]
    # No cap: everything.
    assert eng._warmup_views() == [128, 256]
    # cap 100 + 2*4+1 pad = 109 -> bucket 128 only.
    os.environ["TUNNEL_WARMUP_VIEW_CAP"] = "100"
    try:
        assert eng._warmup_views() == [128]
        # cap 140 -> need 149 -> bucket 256: keeps both.
        os.environ["TUNNEL_WARMUP_VIEW_CAP"] = "140"
        assert eng._warmup_views() == [128, 256]
    finally:
        del os.environ["TUNNEL_WARMUP_VIEW_CAP"]
