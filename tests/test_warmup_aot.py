"""Parallel AOT warmup (engine._warm_aot_parallel) equivalence tests.

The warmup's phase A AOT-compiles every warm program from concurrent
threads via ``jit.lower(...).compile()`` and relies on the persistent
compilation cache to hand those executables back to the serial execute
pass (and to live dispatch).  That only works if the AOT-lowered programs
hash IDENTICALLY to the ones live dispatch builds — any aval drift
(shape/dtype/static-arg mismatch in _decode_warm_args/_chunk_warm_args)
silently doubles compile work on the serving path, which on the
tunneled-TPU deployment costs a whole chip window (PERF.md r5).

The hash-identity proof: warm up engine A with the AOT phase ON, snapshot
the persistent-cache file set, then warm up an identically-configured
engine B with the AOT phase OFF — B's serial compiles must ALL hit the
persistent cache, i.e. add zero new files.
"""

import asyncio
import os

import pytest

import jax

from p2p_llm_tunnel_tpu.engine.engine import EngineConfig, InferenceEngine
from p2p_llm_tunnel_tpu.engine.tokenizer import ByteTokenizer

pytestmark = pytest.mark.slow

ECFG = dict(
    model="tiny", num_slots=4, max_seq=256, dtype="float32", seed=0,
    decode_steps=4, decode_steps_eager=2, prefill_rows=2,
    prefix_cache=True,
)


async def _collect(engine, prompt, max_new=8):
    out = []
    async for ev in engine.generate(prompt, max_new_tokens=max_new,
                                    stop_ids=()):
        out.append(ev.token_id)
    return out


def _cache_files(path):
    return {f for f in os.listdir(path)}


@pytest.fixture()
def persistent_cache(tmp_path, monkeypatch):
    old_dir = jax.config.jax_compilation_cache_dir
    old_min = jax.config.jax_persistent_cache_min_compile_time_secs
    jax.config.update("jax_compilation_cache_dir", str(tmp_path))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    # The cache object binds its directory at first use: without a reset a
    # SECOND test in the same process keeps writing to the first test's
    # (already-asserted) tmp dir and its own stays empty.
    from jax._src import compilation_cache

    compilation_cache.reset_cache()
    yield str(tmp_path)
    jax.config.update("jax_compilation_cache_dir", old_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", old_min)
    compilation_cache.reset_cache()


PROMPT = ByteTokenizer().encode("hello aot")


def test_aot_programs_hash_identical_to_dispatch(persistent_cache,
                                                 monkeypatch):
    monkeypatch.setenv("TUNNEL_WARMUP_VIEW_CAP", "100")
    # Covers the prefill-hint path: the live generate below prefills
    # len(PROMPT) tokens, so its bucket must be AOT-warmed too.
    monkeypatch.setenv("TUNNEL_WARMUP_PREFILL_TOKENS", str(len(PROMPT)))

    marks = {}

    async def run(par):
        monkeypatch.setenv("TUNNEL_WARMUP_PAR", par)
        eng = InferenceEngine(
            engine_cfg=EngineConfig(**ECFG), tokenizer=ByteTokenizer()
        )
        await eng.start()
        await eng.warmup()
        marks[f"warm{par}"] = _cache_files(persistent_cache)
        toks = await _collect(eng, PROMPT)
        await eng.stop()
        return toks

    toks_a = asyncio.run(run("2"))
    files_after_a = _cache_files(persistent_cache)
    assert marks["warm2"], "AOT warmup wrote nothing to the cache"
    # Live dispatch (prefill wave + decode bursts + prefix insert) must
    # hit only pre-warmed programs — any new cache file means a warm-args
    # builder drifted from its live call and a fresh compile landed on
    # the serving path.
    live_new = files_after_a - marks["warm2"]
    assert not live_new, (
        f"live dispatch compiled {len(live_new)} programs warmup missed"
    )

    toks_b = asyncio.run(run("0"))
    new = _cache_files(persistent_cache) - files_after_a
    assert not new, (
        f"serial warmup compiled {len(new)} programs the AOT phase "
        f"missed or mis-hashed"
    )
    assert toks_a == toks_b


def test_warmup_view_cap():
    """Cap arithmetic mirrors _kv_view_bucket's pipelining pad."""
    eng = InferenceEngine(
        engine_cfg=EngineConfig(**{**ECFG, "prefix_cache": False}),
        tokenizer=ByteTokenizer(),
    )
    # max_seq 256 -> full bucket list [128, 256].
    assert eng._view_buckets() == [128, 256]
    # No cap: everything.
    assert eng._warmup_views() == [128, 256]
    # cap 100 + 2*4+1 pad = 109 -> bucket 128 only.
    os.environ["TUNNEL_WARMUP_VIEW_CAP"] = "100"
    try:
        assert eng._warmup_views() == [128]
        # cap 140 -> need 149 -> bucket 256: keeps both.
        os.environ["TUNNEL_WARMUP_VIEW_CAP"] = "140"
        assert eng._warmup_views() == [128, 256]
    finally:
        del os.environ["TUNNEL_WARMUP_VIEW_CAP"]


def test_fused_decode_variants_covered_by_warmup(persistent_cache,
                                                 monkeypatch):
    """ISSUE 4 acceptance: every fused decode-layer variant is covered by
    warmup — after ``warmup()`` returns, live dispatch (prefill wave +
    fused decode bursts + prefix insert, over the fused+int8-KV engine,
    the richest fused program set) adds ZERO fresh compiles.

    Unlike the base test above, the serial par=0 replay engine is not
    re-warmed for a cross-engine hash comparison: JAX numbers outlined
    StableHLO helpers (``@clip_N``) with a PROCESS-GLOBAL counter, so a
    second engine's lowering text — and persistent-cache hash — can shift
    with unrelated prior lowerings in the same process.  The operational
    guarantee (no compile lands on the serving path) is per-engine and is
    what this test pins; the cross-engine identity for the plain config
    stays pinned above."""
    from dataclasses import replace

    from p2p_llm_tunnel_tpu.models.config import get_config

    monkeypatch.setenv("TUNNEL_WARMUP_VIEW_CAP", "100")
    monkeypatch.setenv("TUNNEL_WARMUP_PREFILL_TOKENS", str(len(PROMPT)))
    monkeypatch.setenv("TUNNEL_WARMUP_PAR", "2")
    tok = ByteTokenizer()
    mcfg = replace(
        get_config("tiny", vocab_size=tok.vocab_size), flash_interpret=True
    )

    async def run():
        eng = InferenceEngine(
            model_cfg=mcfg,
            engine_cfg=EngineConfig(
                **{**ECFG, "kv_quant": "int8", "fused_decode_layer": True}
            ),
            tokenizer=ByteTokenizer(),
        )
        await eng.start()
        await eng.warmup()
        warmed = _cache_files(persistent_cache)
        toks = await _collect(eng, PROMPT)
        await eng.stop()
        return toks, warmed

    toks, warmed = asyncio.run(run())
    assert warmed, "warmup wrote nothing to the persistent cache"
    assert len(toks) == 8
    live_new = _cache_files(persistent_cache) - warmed
    assert not live_new, (
        f"live dispatch compiled {len(live_new)} fused programs warmup missed"
    )


def test_ragged_mux_herd_hits_zero_cold_compiles(persistent_cache,
                                                 monkeypatch):
    """ISSUE 15 acceptance: under the RAGGED prefill path the warmup
    grid is the collapsed one — decode view×steps plus ONE ragged
    flat-bucket program (warmup_plan: the whole chunk[t, view] family
    gone) — and it is still COMPLETE: a multiplexed shared-prefix herd
    with short-tail, multi-segment, prefix-hit, and mid-decode
    admissions adds ZERO fresh compiles, and the engine's cold-compile
    counter stays at zero."""
    monkeypatch.setenv("TUNNEL_WARMUP_VIEW_CAP", "100")
    monkeypatch.setenv("TUNNEL_WARMUP_PAR", "2")
    from p2p_llm_tunnel_tpu.utils.metrics import global_metrics

    tok = ByteTokenizer()

    async def run():
        eng = InferenceEngine(
            engine_cfg=EngineConfig(
                **{**ECFG, "mux": True, "ragged_prefill": True}
            ),
            tokenizer=tok,
        )
        assert eng.ecfg.ragged_prefill, eng.config_fences
        assert [k for k, _s in eng.warmup_plan() if k == "chunk"] == []
        await eng.start()
        await eng.warmup()
        warmed = _cache_files(persistent_cache)
        cold0 = global_metrics.counter("engine_cold_compiles_total")
        shared = list(range(1, 81))  # 5 pooled blocks of 16
        herd = [shared + [100 + i] for i in range(3)]  # short tails
        herd.append(list(range(1, 91)))  # multi-segment (90 > chunk 64)
        outs = await asyncio.gather(*(_collect(eng, p) for p in herd))
        # Mid-decode admission + a warm prefix-hit tail.
        outs.append(await _collect(eng, shared + [200]))
        cold = global_metrics.counter("engine_cold_compiles_total") - cold0
        await eng.stop()
        return outs, warmed, cold

    outs, warmed, cold = asyncio.run(run())
    assert warmed, "warmup wrote nothing to the persistent cache"
    assert all(len(o) == 8 for o in outs)
    assert cold == 0, f"{cold} mid-serve cold compiles under ragged mux"
    live_new = _cache_files(persistent_cache) - warmed
    assert not live_new, (
        f"ragged multiplexed herd compiled {len(live_new)} programs "
        f"warmup missed"
    )


def test_mux_herd_hits_zero_cold_compiles(persistent_cache, monkeypatch):
    """ISSUE 5 warmup coverage: under the MULTIPLEXED serving loop, every
    program the scheduler can reach — both burst sizes x every view
    bucket, the chunk program at the (defaulted) segment width x every
    view a padded tail can bucket to (the cap + prefill_chunk term of
    _warmup_views), the prefix copy ops, and the single batched-segment
    row shape (rows always pad to prefill_rows, so the budget controller
    cannot mint new shapes) — is compiled by warmup(); a multiplexed
    shared-prefix herd with multi-segment, short-tail, and mid-decode
    admissions then adds ZERO fresh compiles."""
    monkeypatch.setenv("TUNNEL_WARMUP_VIEW_CAP", "100")
    monkeypatch.setenv("TUNNEL_WARMUP_PAR", "2")
    tok = ByteTokenizer()

    async def run():
        eng = InferenceEngine(
            engine_cfg=EngineConfig(**{**ECFG, "mux": True}),
            tokenizer=tok,
        )
        await eng.start()
        await eng.warmup()
        warmed = _cache_files(persistent_cache)
        shared = list(range(1, 81))  # 5 pooled blocks of 16
        herd = [shared + [100 + i] for i in range(3)]  # short tails
        herd.append(list(range(1, 91)))  # multi-segment (90 > chunk 64)
        outs = await asyncio.gather(*(_collect(eng, p) for p in herd))
        # Mid-decode admission: the budget controller's interleave path.
        outs.append(await _collect(eng, shared + [200]))
        await eng.stop()
        return outs, warmed

    outs, warmed = asyncio.run(run())
    assert warmed, "warmup wrote nothing to the persistent cache"
    assert all(len(o) == 8 for o in outs)
    live_new = _cache_files(persistent_cache) - warmed
    assert not live_new, (
        f"multiplexed herd compiled {len(live_new)} programs warmup missed"
    )


def test_mux_spec_herd_hits_zero_cold_compiles(persistent_cache,
                                               monkeypatch):
    """ISSUE 17 acceptance: warmup_plan() enumerates the fused spec-verify
    program per (view, K) — the whole adaptive power-of-two K ladder, not
    just the configured burst width — so a multiplexed spec-on herd of
    repetitive prompts (the ngram proposer fires constantly, so verify
    bursts really dispatch) serves with engine_cold_compiles_total == 0
    and adds no fresh persistent-cache entries."""
    monkeypatch.setenv("TUNNEL_WARMUP_VIEW_CAP", "100")
    monkeypatch.setenv("TUNNEL_WARMUP_PAR", "2")
    from p2p_llm_tunnel_tpu.utils.metrics import global_metrics

    tok = ByteTokenizer()
    rep = list(b"the cat sat on the mat. the cat sat on the mat. the cat")

    async def run():
        eng = InferenceEngine(
            engine_cfg=EngineConfig(
                **{**ECFG, "mux": True, "spec_ngram": 3, "spec_k": 2,
                   "spec_k_max": 4}
            ),
            tokenizer=tok,
        )
        spec_shapes = [s for kind, s in eng.warmup_plan() if kind == "spec"]
        assert spec_shapes, "warmup plan lost the spec-verify programs"
        # Every view bucket appears with every K bucket of the ladder
        # (adaptive mode: powers of two up to the cap, down to K=1).
        assert {k for _v, k in spec_shapes} == {1, 2, 4}
        await eng.start()
        await eng.warmup()
        warmed = _cache_files(persistent_cache)
        cold0 = global_metrics.counter("engine_cold_compiles_total")
        spec0 = global_metrics.counter("engine_spec_proposed_tokens_total")
        herd = [rep + [100 + i] for i in range(3)]
        outs = await asyncio.gather(
            *(_collect(eng, p, max_new=24) for p in herd))
        # Mid-decode admission while verify bursts are in flight.
        outs.append(await _collect(eng, rep + [200], max_new=24))
        cold = global_metrics.counter("engine_cold_compiles_total") - cold0
        fired = (global_metrics.counter("engine_spec_proposed_tokens_total")
                 - spec0)
        await eng.stop()
        return outs, warmed, cold, fired

    outs, warmed, cold, fired = asyncio.run(run())
    assert warmed, "warmup wrote nothing to the persistent cache"
    assert all(len(o) == 24 for o in outs)
    assert fired > 0, "the spec-on herd never dispatched a verify burst"
    assert cold == 0, f"{cold} mid-serve cold compiles under mux+spec"
    live_new = _cache_files(persistent_cache) - warmed
    assert not live_new, (
        f"mux+spec herd compiled {len(live_new)} programs warmup missed"
    )
