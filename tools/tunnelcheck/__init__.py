"""tunnelcheck: project-native static analysis for the tunnel codebase.

Stdlib-only (``ast``-based) rules that make this repo's recurring runtime
bug classes statically detectable.  Two layers since ISSUE 11: a shared
analysis substrate (``dataflow.py`` — per-function CFGs with await-point
partitioning, reaching reads over shared attributes, a taint lattice —
and ``callgraph.py`` — the project-wide call graph) and one rule module
per bug family on top.  See README.md "Static analysis & invariants" for
the TC01–TC15 rule table and the incidents each rule guards against.

Usage::

    python -m tools.tunnelcheck p2p_llm_tunnel_tpu scripts tests
    python -m tools.tunnelcheck ... --jobs auto --sarif out.sarif
    python -m tools.tunnelcheck ... --changed-only

Waive a single finding on its line::

    time.sleep(0.1)  # tunnelcheck: disable=TC01  <why this one is fine>

or a whole file (fixture trees, generated code)::

    # tunnelcheck: disable-file=TC03
"""

from tools.tunnelcheck.core import (  # noqa: F401
    ProjectContext,
    SourceFile,
    Violation,
    all_rules,
    run_paths,
)
