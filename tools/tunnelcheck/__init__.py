"""tunnelcheck: project-native static analysis for the tunnel codebase.

Stdlib-only (``ast``-based) rules that make this repo's recurring runtime
bug classes statically detectable.  See README.md "Static analysis &
invariants" for the rule table and the incidents each rule guards against.

Usage::

    python -m tools.tunnelcheck p2p_llm_tunnel_tpu scripts tests

Waive a single finding on its line::

    time.sleep(0.1)  # tunnelcheck: disable=TC01  <why this one is fine>

or a whole file (fixture trees, generated code)::

    # tunnelcheck: disable-file=TC03
"""

from tools.tunnelcheck.core import (  # noqa: F401
    ProjectContext,
    SourceFile,
    Violation,
    all_rules,
    run_paths,
)
