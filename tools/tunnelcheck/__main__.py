"""CLI: ``python -m tools.tunnelcheck p2p_llm_tunnel_tpu scripts tests``.

Exit status: 0 = clean, 1 = violations, 2 = usage error.

``--jobs N`` fans the per-file rule passes across a fork pool (``auto``
picks the CPU count); ``--changed-only`` restricts FINDINGS to files git
reports as changed while the whole path set still feeds cross-file
context; ``--sarif out.json`` writes the machine-consumable SARIF 2.1.0
log alongside the human output; ``--cache DIR`` enables the per-file
result cache (a warm no-change run skips the whole check phase);
``--waiver-audit`` prints stale ``# tunnelcheck: disable=`` comments as
warnings (never exit-code-affecting); ``--budget-s N`` fails the run when
wall time exceeds the budget, so an interprocedural regression cannot
silently slow the dev loop.

The printed summary and the exit code are computed from the SAME
violation list — TC00 parse errors included — so they can never disagree
(the unparseable-file counting bug class is pinned by a fixture test).
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
import time
from pathlib import Path
from typing import Optional, Set

from tools.tunnelcheck.core import (
    REPO_ROOT,
    RULE_SUMMARIES,
    all_rules,
    iter_python_files,
    run_paths,
)


def _git_changed_files(root: Path) -> Optional[Set[Path]]:
    """Resolved paths of files git sees as modified/added/untracked, or
    None when git is unavailable (callers fall back to a full run)."""
    out: Set[Path] = set()
    try:
        for args in (
            ["git", "diff", "--name-only", "HEAD", "--"],
            ["git", "ls-files", "--others", "--exclude-standard"],
        ):
            proc = subprocess.run(
                args, cwd=root, capture_output=True, text=True, timeout=30,
            )
            if proc.returncode != 0:
                return None
            for line in proc.stdout.splitlines():
                line = line.strip()
                if line:
                    out.add((root / line).resolve())
    except (OSError, subprocess.SubprocessError):
        return None
    return out


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="tunnelcheck",
        description="Project-native static analysis for the tunnel codebase.",
    )
    parser.add_argument("paths", nargs="*", help="files or directories to scan")
    parser.add_argument(
        "--rules",
        help="comma-separated rule ids to run (default: all)",
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="print the rule table and exit"
    )
    parser.add_argument(
        "--show-waived",
        action="store_true",
        help="also print findings silenced by `# tunnelcheck: disable=` waivers",
    )
    parser.add_argument(
        "--jobs",
        default="1",
        help="worker processes for the rule passes (an int, or `auto` "
        "for the CPU count); cross-file context is built once and "
        "fork-shared",
    )
    parser.add_argument(
        "--changed-only",
        action="store_true",
        help="report findings only for files git sees as changed "
        "(modified/added/untracked vs HEAD); the full path set still "
        "feeds cross-file context, so TC02/TC06/TC07 resolution is "
        "identical to a full run",
    )
    parser.add_argument(
        "--sarif",
        metavar="OUT.json",
        help="also write findings (waived included, as suppressed results) "
        "as a SARIF 2.1.0 log",
    )
    parser.add_argument(
        "--cache",
        metavar="DIR",
        help="per-file result cache directory (keyed on file content, the "
        "rule-module digest, and the whole-tree digest — interprocedural "
        "rules make per-file isolation unsound, so any edit invalidates "
        "everything); ignored with --changed-only",
    )
    parser.add_argument(
        "--waiver-audit",
        action="store_true",
        help="warn about `# tunnelcheck: disable=` comments whose rule no "
        "longer fires on the annotated statement (stale-waiver rot); "
        "warnings never affect the exit code",
    )
    parser.add_argument(
        "--budget-s",
        type=float,
        metavar="SECONDS",
        help="fail (exit 1) when the run's wall time exceeds this budget",
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule_id in sorted(RULE_SUMMARIES):
            print(f"{rule_id}  {RULE_SUMMARIES[rule_id]}")
        return 0

    if not args.paths:
        parser.print_usage(sys.stderr)
        print("tunnelcheck: error: no paths given", file=sys.stderr)
        return 2

    paths = [Path(p) for p in args.paths]
    for p in paths:
        if not p.exists():
            print(f"tunnelcheck: error: no such path: {p}", file=sys.stderr)
            return 2

    selected = None
    if args.rules:
        selected = [r.strip() for r in args.rules.split(",") if r.strip()]
        # TC00 (parse errors) is always on and unfilterable; accept it in
        # the filter so every id shown by --list-rules is valid here.
        unknown = set(selected) - set(all_rules()) - {"TC00"}
        if unknown:
            print(
                f"tunnelcheck: error: unknown rule(s): {', '.join(sorted(unknown))}",
                file=sys.stderr,
            )
            return 2

    if args.jobs == "auto":
        jobs = os.cpu_count() or 1
    else:
        try:
            jobs = int(args.jobs)
        except ValueError:
            print(f"tunnelcheck: error: bad --jobs value: {args.jobs!r}",
                  file=sys.stderr)
            return 2
    jobs = max(1, jobs)

    restrict: Optional[Set[Path]] = None
    if args.changed_only:
        changed = _git_changed_files(REPO_ROOT)
        if changed is None:
            print(
                "tunnelcheck: --changed-only: git unavailable, running on "
                "everything",
                file=sys.stderr,
            )
        else:
            restrict = {
                f.resolve() for f in iter_python_files(paths)
            } & changed

    root = Path.cwd()
    stats: dict = {}
    audit: Optional[list] = [] if args.waiver_audit else None
    cache_dir = Path(args.cache) if args.cache else None
    t0 = time.monotonic()
    active, waived = run_paths(
        paths, rules=selected, stats=stats, jobs=jobs, restrict=restrict,
        cache_dir=cache_dir, waiver_audit=audit,
    )
    elapsed = time.monotonic() - t0
    for v in active:
        print(v.render(root))
    if args.show_waived:
        for v in waived:
            print(f"{v.render(root)} [waived]")
    if audit:
        for path, line, msg in audit:
            p = path
            try:
                p = path.relative_to(root)
            except ValueError:
                pass
            print(f"{p}:{line}: waiver-audit: {msg}", file=sys.stderr)

    if args.sarif:
        from tools.tunnelcheck.sarif import write_sarif

        write_sarif(Path(args.sarif), active, waived, root=root)

    checked = (
        f"{len(restrict)} changed of {stats.get('files', 0)}"
        if restrict is not None
        else f"{stats.get('files', 0)}"
    )
    cache_note = ""
    if cache_dir is not None and restrict is None:
        cache_note = (
            f", cache: {stats.get('cache_hits', 0)} hit(s) "
            f"{stats.get('cache_misses', 0)} miss(es)"
        )
    summary = (
        f"tunnelcheck: {len(active)} violation(s), {len(waived)} waived, "
        f"{checked} file(s) scanned in {elapsed:.2f}s"
        f" ({jobs} job(s){cache_note})"
    )
    if audit:
        summary += f" [{len(audit)} stale waiver(s)]"
    print(summary, file=sys.stderr)
    if args.budget_s is not None and elapsed > args.budget_s:
        print(
            f"tunnelcheck: error: wall time {elapsed:.2f}s exceeded the "
            f"--budget-s {args.budget_s:g}s budget",
            file=sys.stderr,
        )
        return 1
    return 1 if active else 0


if __name__ == "__main__":
    sys.exit(main())
