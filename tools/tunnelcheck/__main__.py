"""CLI: ``python -m tools.tunnelcheck p2p_llm_tunnel_tpu scripts tests``.

Exit status: 0 = clean, 1 = violations, 2 = usage error.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

from tools.tunnelcheck.core import RULE_SUMMARIES, all_rules, run_paths


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="tunnelcheck",
        description="Project-native static analysis for the tunnel codebase.",
    )
    parser.add_argument("paths", nargs="*", help="files or directories to scan")
    parser.add_argument(
        "--rules",
        help="comma-separated rule ids to run (default: all)",
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="print the rule table and exit"
    )
    parser.add_argument(
        "--show-waived",
        action="store_true",
        help="also print findings silenced by `# tunnelcheck: disable=` waivers",
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule_id in sorted(RULE_SUMMARIES):
            print(f"{rule_id}  {RULE_SUMMARIES[rule_id]}")
        return 0

    if not args.paths:
        parser.print_usage(sys.stderr)
        print("tunnelcheck: error: no paths given", file=sys.stderr)
        return 2

    paths = [Path(p) for p in args.paths]
    for p in paths:
        if not p.exists():
            print(f"tunnelcheck: error: no such path: {p}", file=sys.stderr)
            return 2

    selected = None
    if args.rules:
        selected = [r.strip() for r in args.rules.split(",") if r.strip()]
        # TC00 (parse errors) is always on and unfilterable; accept it in
        # the filter so every id shown by --list-rules is valid here.
        unknown = set(selected) - set(all_rules()) - {"TC00"}
        if unknown:
            print(
                f"tunnelcheck: error: unknown rule(s): {', '.join(sorted(unknown))}",
                file=sys.stderr,
            )
            return 2

    root = Path.cwd()
    stats: dict = {}
    active, waived = run_paths(paths, rules=selected, stats=stats)
    for v in active:
        print(v.render(root))
    if args.show_waived:
        for v in waived:
            print(f"{v.render(root)} [waived]")
    summary = (
        f"tunnelcheck: {len(active)} violation(s), {len(waived)} waived, "
        f"{stats.get('files', 0)} file(s) scanned"
    )
    print(summary, file=sys.stderr)
    return 1 if active else 0


if __name__ == "__main__":
    sys.exit(main())
