"""Project-wide call graph: the cross-file resolution TC02 half-built,
promoted to a shared substrate layer.

Before this module, three rules each carried a private sliver of the same
graph: TC02 resolved jitted callables through ``ProjectContext``'s flat
function index, TC07 re-derived "functions whose body calls ``jax.jit``"
with its own project scan plus a per-module transitive-dispatch closure,
and TC03 kept a same-file def index.  One drifting copy per rule is the
config-rot bug class (TC08) applied to the checker itself — so the graph
now lives here, built once per run, cached on the
:class:`~tools.tunnelcheck.core.ProjectContext`.

The graph is *name-keyed and over-approximate*: an edge ``f → g`` exists
when ``f``'s body contains a call whose callee (bare name or resolved
dotted path) is ``g``.  Dynamic dispatch, aliasing through containers, and
higher-order flow are invisible — rules that need soundness in one
direction (TC07: "could this loop body reach a device dispatch?") want
exactly this over-approximation, and rules that need a unique signature
(TC02) go through :meth:`resolve`, which refuses ambiguous answers rather
than guessing.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from pathlib import Path
from typing import Callable, Dict, List, Optional, Sequence, Set

from tools.tunnelcheck.core import FuncInfo, SourceFile, resolve_dotted


@dataclass
class FuncNode:
    """One def in the project: its statically-extracted signature, the
    class that owns it (if a method), and its outgoing call edges."""

    info: FuncInfo
    node: ast.AST
    cls: Optional[str]
    path: Path
    #: Bare callee names of every call in the body (``obj.meth`` → "meth").
    calls: Set[str] = field(default_factory=set)
    #: Canonical dotted callees resolvable through the module's imports
    #: ("jnp.abs" → "jax.numpy.abs").
    dotted_calls: Set[str] = field(default_factory=set)

    @property
    def name(self) -> str:
        return self.info.name

    @property
    def qualname(self) -> str:
        return f"{self.cls}.{self.info.name}" if self.cls else self.info.name


class CallGraph:
    """All defs in the scanned set, with name-keyed call edges."""

    def __init__(self, files: Sequence[SourceFile]):
        self.files = list(files)
        #: bare name -> every def carrying it, in scan order.
        self.by_name: Dict[str, List[FuncNode]] = {}
        #: per-file view, for rules whose scope is one module.
        self.by_path: Dict[Path, List[FuncNode]] = {}
        #: functions_calling() memo — TC07 asks for the jax.jit factories
        #: once per in-scope file, and the project-wide sweep must stay a
        #: once-per-run cost like the private cache it replaced.
        self._calling_cache: Dict[str, Set[str]] = {}
        for sf in files:
            self._index_file(sf)

    def _index_file(self, sf: SourceFile) -> None:
        nodes = self.by_path.setdefault(sf.path, [])

        def visit(body, cls: Optional[str], class_depth: int) -> None:
            for stmt in body:
                if isinstance(stmt, ast.ClassDef):
                    visit(stmt.body, stmt.name, class_depth + 1)
                elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    deco = {
                        resolve_dotted(d, sf.aliases)
                        for d in stmt.decorator_list
                    }
                    is_method = class_depth > 0 and not (
                        deco & {"staticmethod", "classmethod"}
                    )
                    fn = FuncNode(
                        info=FuncInfo.from_node(stmt, sf.path, is_method=is_method),
                        node=stmt,
                        cls=cls if class_depth > 0 else None,
                        path=sf.path,
                    )
                    for sub in ast.walk(stmt):
                        if isinstance(sub, ast.Call):
                            if isinstance(sub.func, ast.Attribute):
                                fn.calls.add(sub.func.attr)
                            elif isinstance(sub.func, ast.Name):
                                fn.calls.add(sub.func.id)
                            resolved = resolve_dotted(sub.func, sf.aliases)
                            if resolved:
                                fn.dotted_calls.add(resolved)
                    self.by_name.setdefault(stmt.name, []).append(fn)
                    nodes.append(fn)
                    visit(stmt.body, None, 0)  # nested defs: not methods
                else:
                    # Fully recurse into compound statements (if/try/with/
                    # loops, except handlers) at the SAME class context —
                    # a def inside an except handler or a doubly-nested if
                    # must be indexed exactly like the old ast.walk-based
                    # per-rule indexers did, or TC02/TC03/TC07/TC09 lose
                    # coverage silently.
                    for _field, value in ast.iter_fields(stmt):
                        if not isinstance(value, list) or not value:
                            continue
                        if isinstance(value[0], ast.stmt):
                            visit(value, cls, class_depth)
                        elif isinstance(value[0], ast.excepthandler):
                            for handler in value:
                                visit(handler.body, cls, class_depth)
                        elif isinstance(value[0], ast.match_case):
                            for case in value:
                                visit(case.body, cls, class_depth)

        visit(sf.tree.body, None, 0)

    # -- signature resolution (TC02's consumer) ---------------------------

    def resolve(
        self, name: str, prefer_path: Optional[Path] = None
    ) -> Optional[FuncInfo]:
        """The unique signature for ``name``, or None when absent or
        ambiguous.  Same-file defs win; otherwise every project-wide def
        must agree on shape — a common helper name with divergent
        signatures is skipped rather than guessed at."""
        nodes = self.by_name.get(name)
        if not nodes:
            return None
        infos = [n.info for n in nodes]
        if prefer_path is not None:
            local = [i for i in infos if i.path == prefer_path]
            if len(local) == 1:
                return local[0]
            if len(local) > 1:
                infos = local
        shapes = {
            (tuple(i.pos), i.n_pos_defaults, tuple(i.kwonly), i.has_vararg,
             i.has_kwarg, i.is_method)
            for i in infos
        }
        return infos[0] if len(shapes) == 1 else None

    # -- closures (TC07's consumers) --------------------------------------

    def functions_calling(self, dotted: str) -> Set[str]:
        """Bare names of every def (project-wide) whose body calls the
        canonical dotted path — e.g. ``jax.jit`` finds the jit factories
        whose returned callables are device dispatches.  Memoized per
        run; the graph is immutable once built."""
        cached = self._calling_cache.get(dotted)
        if cached is None:
            cached = {
                name
                for name, nodes in self.by_name.items()
                if any(dotted in n.dotted_calls for n in nodes)
            }
            self._calling_cache[dotted] = cached
        return cached

    def transitive_callers(
        self,
        seeds: Callable[[FuncNode], bool],
        within: Optional[Path] = None,
    ) -> Set[str]:
        """Names of defs that transitively CALL a seed (a def for which
        ``seeds(node)`` is True) through name-keyed edges.  ``within``
        restricts both the candidate set and the edge targets to one file
        — TC07's per-module dispatch closure — while seeds themselves are
        judged wherever they are defined."""
        nodes = self.by_path.get(within, []) if within is not None else [
            n for ns in self.by_name.values() for n in ns
        ]
        marked: Set[str] = {n.name for n in nodes if seeds(n)}
        changed = True
        while changed:
            changed = False
            for n in nodes:
                if n.name in marked:
                    continue
                if n.calls & marked:
                    marked.add(n.name)
                    changed = True
        return marked
