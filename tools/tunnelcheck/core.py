"""Rule framework: source loading, project context, waivers, the runner.

Everything here is stdlib-only on purpose (ISSUE 3): the checker must run
in any environment that can run the repo's tests — including ones without
jax, websockets, or cryptography installed — so rules work on the ``ast``
of the code, never by importing it.
"""

from __future__ import annotations

import ast
import hashlib
import io
import json
import re
import sys
import tokenize
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Set, Tuple

#: Repo root, derived from this file's location (tools/tunnelcheck/core.py),
#: so registry files (protocol/frames.py, utils/metrics.py) resolve even when
#: the scan targets are test fixtures outside the tree.
REPO_ROOT = Path(__file__).resolve().parents[2]

# The id list stops at the first space so a waiver can carry a justification:
#   time.sleep(1)  # tunnelcheck: disable=TC01  startup-only, loop not running
_RULE_LIST = r"([A-Za-z0-9_]+(?:\s*,\s*[A-Za-z0-9_]+)*)"
_WAIVER_RE = re.compile(r"#\s*tunnelcheck:\s*disable=" + _RULE_LIST)
_FILE_WAIVER_RE = re.compile(r"#\s*tunnelcheck:\s*disable-file=" + _RULE_LIST)


@dataclass
class Violation:
    rule: str
    path: Path
    line: int
    message: str
    #: Last line of the offending statement: a waiver comment anywhere on
    #: the statement (e.g. next to one argument of a multi-line call)
    #: suppresses, not just one on the anchor line.
    end_line: Optional[int] = None

    def render(self, root: Optional[Path] = None) -> str:
        p = self.path
        if root is not None:
            try:
                p = p.relative_to(root)
            except ValueError:
                pass
        return f"{p}:{self.line}: {self.rule} {self.message}"


@dataclass
class SourceFile:
    path: Path
    text: str
    tree: ast.Module
    lines: List[str]
    #: local name -> canonical dotted path ("jnp" -> "jax.numpy").
    aliases: Dict[str, str]
    #: line number -> set of waived rule ids ("all" waives everything).
    line_waivers: Dict[int, Set[str]] = field(default_factory=dict)
    file_waivers: Set[str] = field(default_factory=set)

    def waived(self, rule: str, line: int, end_line: Optional[int] = None) -> bool:
        if "all" in self.file_waivers or rule in self.file_waivers:
            return True
        for ln in range(line, (end_line or line) + 1):
            w = self.line_waivers.get(ln, ())
            if "all" in w or rule in w:
                return True
        return False


@dataclass
class FuncInfo:
    """Statically-extracted signature of one def/lambda."""

    name: str
    pos: List[str]  # positional-only + positional-or-keyword, in order
    n_pos_defaults: int
    kwonly: List[str]
    kwonly_required: List[str]
    has_vararg: bool
    has_kwarg: bool
    is_method: bool  # defined directly inside a class, not static/classmethod
    path: Path
    line: int

    @classmethod
    def from_node(
        cls,
        node: "ast.FunctionDef | ast.AsyncFunctionDef | ast.Lambda",
        path: Path,
        is_method: bool = False,
    ) -> "FuncInfo":
        a = node.args
        kw_required = [
            arg.arg
            for arg, d in zip(a.kwonlyargs, a.kw_defaults)
            if d is None
        ]
        return cls(
            name=getattr(node, "name", "<lambda>"),
            pos=[x.arg for x in a.posonlyargs + a.args],
            n_pos_defaults=len(a.defaults),
            kwonly=[x.arg for x in a.kwonlyargs],
            kwonly_required=kw_required,
            has_vararg=a.vararg is not None,
            has_kwarg=a.kwarg is not None,
            is_method=is_method,
            path=path,
            line=getattr(node, "lineno", 0),
        )

    def effective_pos(self, drop_self: bool) -> List[str]:
        return self.pos[1:] if (drop_self and self.is_method and self.pos) else self.pos


def _is_type_checking_test(test: ast.AST) -> bool:
    d = dotted_name(test)
    return d is not None and d.split(".")[-1] == "TYPE_CHECKING"


def iter_scope_statements(body: Iterable[ast.stmt]) -> Iterator[ast.AST]:
    """Statements executed AT RUNTIME in the scope owning ``body`` —
    descends into try/if/with/loop (and class) blocks but never into nested
    functions (bindings local to them) nor ``if TYPE_CHECKING:`` bodies
    (which never execute).  SOURCE ORDER is preserved so a rebound import
    name resolves to its last binding, like Python does."""
    for node in body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        if isinstance(node, ast.If) and _is_type_checking_test(node.test):
            yield from iter_scope_statements(node.orelse)
            continue
        yield node
        yield from iter_scope_statements(ast.iter_child_nodes(node))


def collect_import_aliases(
    nodes: Iterable[ast.AST], out: Optional[Dict[str, str]] = None
) -> Dict[str, str]:
    out = {} if out is None else out
    for node in nodes:
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.asname:
                    out[a.asname] = a.name
                else:
                    root = a.name.split(".")[0]
                    out[root] = root
        elif isinstance(node, ast.ImportFrom):
            if node.level or not node.module:
                continue  # relative imports stay project-local
            for a in node.names:
                if a.name == "*":
                    continue
                out[a.asname or a.name] = f"{node.module}.{a.name}"
    return out


def module_aliases(tree: ast.Module) -> Dict[str, str]:
    """Map each MODULE-LEVEL import name to its canonical dotted origin.

    Function-local imports are deliberately excluded: a helper's
    ``from time import sleep`` must not make every other function's
    ``sleep`` resolve to ``time.sleep``.  Rules that care about local
    imports (TC01) overlay them per function scope.
    """
    return collect_import_aliases(iter_scope_statements(tree.body))


def dotted_name(node: ast.AST) -> Optional[str]:
    """"a.b.c" for a Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def resolve_dotted(node: ast.AST, aliases: Dict[str, str]) -> Optional[str]:
    """Canonical dotted path of an expression ("jnp.abs" -> "jax.numpy.abs")."""
    dotted = dotted_name(node)
    if dotted is None:
        return None
    head, _, rest = dotted.partition(".")
    origin = aliases.get(head)
    if origin is None:
        return dotted
    return f"{origin}.{rest}" if rest else origin


def _collect_waivers(text: str) -> Tuple[Dict[int, Set[str]], Set[str]]:
    """Waivers from actual COMMENT tokens, never from string literals —
    a fixture string containing ``# tunnelcheck: disable-file=...`` must not
    waive anything in the file that carries it."""
    per_line: Dict[int, Set[str]] = {}
    whole_file: Set[str] = set()
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(text).readline))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return per_line, whole_file
    for tok in tokens:
        if tok.type != tokenize.COMMENT or "tunnelcheck" not in tok.string:
            continue
        m = _WAIVER_RE.search(tok.string)
        if m:
            per_line.setdefault(tok.start[0], set()).update(
                r.strip() for r in m.group(1).split(",") if r.strip()
            )
        m = _FILE_WAIVER_RE.search(tok.string)
        if m:
            whole_file |= {r.strip() for r in m.group(1).split(",") if r.strip()}
    return per_line, whole_file


def load_source(path: Path) -> Tuple[Optional[SourceFile], Optional[Violation]]:
    try:
        text = path.read_text(encoding="utf-8")
        tree = ast.parse(text, filename=str(path))
    except (OSError, SyntaxError, ValueError) as e:
        line = getattr(e, "lineno", 1) or 1
        return None, Violation("TC00", path, line, f"unparseable: {e}")
    lines = text.splitlines()
    per_line, whole_file = _collect_waivers(text)
    return (
        SourceFile(
            path=path,
            text=text,
            tree=tree,
            lines=lines,
            aliases=module_aliases(tree),
            line_waivers=per_line,
            file_waivers=whole_file,
        ),
        None,
    )


def _parse_registry_file(rel: str, scanned: Sequence[SourceFile]) -> Optional[ast.Module]:
    """AST of a registry module: prefer a scanned copy, else the repo's own."""
    for sf in scanned:
        if sf.path.as_posix().endswith(rel):
            return sf.tree
    candidate = REPO_ROOT / rel
    if candidate.is_file():
        try:
            return ast.parse(candidate.read_text(encoding="utf-8"))
        except (OSError, SyntaxError):
            return None
    return None


def _enum_members(tree: ast.Module, class_name: str) -> List[str]:
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == class_name:
            out = []
            for stmt in node.body:
                if (
                    isinstance(stmt, ast.Assign)
                    and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)
                    and isinstance(stmt.value, ast.Constant)
                    and isinstance(stmt.value.value, int)
                ):
                    out.append(stmt.targets[0].id)
            return out
    return []


def _str_collection(tree: ast.Module, var_name: str) -> Set[str]:
    """String literals in a module-level ``NAME = {...}`` / frozenset / dict."""
    for node in tree.body:
        target = None
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            target = node.targets[0]
            value = node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            target = node.target
            value = node.value
        else:
            continue
        if not (isinstance(target, ast.Name) and target.id == var_name):
            continue
        if isinstance(value, ast.Call):  # frozenset({...})
            if value.args:
                value = value.args[0]
        if isinstance(value, ast.Dict):
            return {
                k.value
                for k in value.keys
                if isinstance(k, ast.Constant) and isinstance(k.value, str)
            }
        if isinstance(value, (ast.Set, ast.List, ast.Tuple)):
            return {
                e.value
                for e in value.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, str)
            }
    return set()


class ProjectContext:
    """Cross-file knowledge shared by all rules for one run."""

    def __init__(self, files: Sequence[SourceFile]):
        self.files = list(files)
        self._callgraph = None
        self._attr_counts: Optional[Dict[str, int]] = None
        self._scoped_graphs: Dict[str, object] = {}
        self._interproc: Dict[str, object] = {}

        frames = _parse_registry_file(
            "p2p_llm_tunnel_tpu/protocol/frames.py", self.files
        )
        self.message_types: List[str] = (
            _enum_members(frames, "MessageType") if frames else []
        )
        self.error_codes: Set[str] = (
            _str_collection(frames, "ERROR_CODES") if frames else set()
        )
        metrics = _parse_registry_file(
            "p2p_llm_tunnel_tpu/utils/metrics.py", self.files
        )
        self.metrics_names: Set[str] = (
            _str_collection(metrics, "METRICS_CATALOG") if metrics else set()
        )
        tracing = _parse_registry_file(
            "p2p_llm_tunnel_tpu/utils/tracing.py", self.files
        )
        self.span_names: Set[str] = (
            _str_collection(tracing, "SPAN_CATALOG") if tracing else set()
        )
        flight = _parse_registry_file(
            "p2p_llm_tunnel_tpu/utils/flight.py", self.files
        )
        self.flight_fields: Set[str] = (
            _str_collection(flight, "FLIGHT_SCHEMA") if flight else set()
        )
        self.postmortem_fields: Set[str] = (
            _str_collection(flight, "POSTMORTEM_SCHEMA") if flight else set()
        )

    @property
    def callgraph(self):
        """The project-wide call graph (tools.tunnelcheck.callgraph), built
        once per run on first use and shared by every rule — the cross-file
        resolution TC02 half-built, now a substrate layer."""
        if self._callgraph is None:
            from tools.tunnelcheck.callgraph import CallGraph

            self._callgraph = CallGraph(self.files)
        return self._callgraph

    def attr_function_count(self, attr: str) -> int:
        """In how many distinct functions (project-wide) is ``attr``
        accessed through any receiver?  TC13's shared-state gate."""
        if self._attr_counts is None:
            from tools.tunnelcheck.dataflow import attr_function_counts

            self._attr_counts = attr_function_counts(
                sf.tree for sf in self.files
            )
        return self._attr_counts.get(attr, 0)

    def scoped_callgraph(self, scope_part: str):
        """Call graph restricted to files whose path contains
        ``scope_part`` — the interprocedural rules analyze the package,
        not the tests/fixtures that happen to share a scan."""
        got = self._scoped_graphs.get(scope_part)
        if got is None:
            from tools.tunnelcheck.callgraph import CallGraph

            got = CallGraph([
                sf for sf in self.files
                if scope_part in sf.path.as_posix()
            ])
            self._scoped_graphs[scope_part] = got
        return got

    def interproc(self, key: str, build):
        """Memoized interprocedural fixpoint shared across the per-file
        rule passes of one run (and warmed before the fork in parallel
        runs, like :attr:`callgraph`)."""
        got = self._interproc.get(key)
        if got is None:
            got = build()
            self._interproc[key] = got
        return got



def iter_python_files(paths: Iterable[Path]) -> Iterator[Path]:
    seen: Set[Path] = set()

    def emit(f: Path) -> Iterator[Path]:
        r = f.resolve()
        if r not in seen:
            seen.add(r)
            yield f

    for p in paths:
        if p.is_dir():
            for f in sorted(p.rglob("*.py")):
                if "__pycache__" not in f.parts:
                    yield from emit(f)
        elif p.suffix == ".py":
            yield from emit(p)


def all_rules() -> Dict[str, "object"]:
    """rule id -> check function ``(SourceFile, ProjectContext) -> Iterator``."""
    from tools.tunnelcheck import (
        rules_async,
        rules_atomicity,
        rules_config,
        rules_deps,
        rules_dispatch,
        rules_flight,
        rules_jax,
        rules_kvalign,
        rules_labels,
        rules_lifecycle,
        rules_metrics,
        rules_protocol,
        rules_queues,
        rules_retry,
        rules_taint,
        rules_tierpin,
        rules_tracing,
        rules_warmup,
    )

    return {
        "TC01": rules_async.check_tc01,
        "TC02": rules_jax.check_tc02,
        "TC03": rules_jax.check_tc03,
        "TC04": rules_deps.check_tc04,
        "TC05": rules_protocol.check_tc05,
        "TC06": rules_metrics.check_tc06,
        "TC07": rules_dispatch.check_tc07,
        "TC08": rules_config.check_tc08,
        "TC09": rules_tracing.check_tc09,
        "TC10": rules_queues.check_tc10,
        "TC11": rules_retry.check_tc11,
        "TC12": rules_labels.check_tc12,
        "TC13": rules_atomicity.check_tc13,
        "TC14": rules_taint.check_tc14,
        "TC15": rules_lifecycle.check_tc15,
        "TC16": rules_flight.check_tc16,
        "TC17": rules_warmup.check_tc17,
        "TC18": rules_tierpin.check_tc18,
        "TC19": rules_kvalign.check_tc19,
        "TC20": rules_tierpin.check_tc20,
        "TC21": rules_taint.check_tc21,
    }


RULE_SUMMARIES = {
    "TC00": "file fails to parse (always on)",
    "TC01": "blocking call (sleep/subprocess/socket/file IO) inside async def",
    "TC02": "jax.jit static/donate argnums+argnames or call arity vs wrapped signature",
    "TC03": "host sync (.item()/np.asarray/device_get/if-on-array) inside traced fns",
    "TC04": "module-level optional-dep import (websockets/cryptography) outside gated wrappers",
    "TC05": "non-exhaustive MessageType dispatch / typed_error code not in ERROR_CODES",
    "TC06": "metric name not declared in utils.metrics.METRICS_CATALOG",
    "TC07": "device dispatch inside a per-request/slot loop on the serving path",
    "TC08": "EngineConfig field not wired to a cli.py flag (config rot)",
    "TC09": "span name not in utils.tracing.SPAN_CATALOG / span emission inside traced fns",
    "TC10": "unbounded Queue/deque in endpoints/transport/protocol without a backpressure waiver",
    "TC11": "retry/backoff loop in cli.py/endpoints/transport without a cap+attempt bound or jitter",
    "TC12": "labeled Prometheus series interpolated outside the bounded registry helpers",
    "TC13": "read-modify-write of shared state straddles an await/yield without a lock",
    "TC14": "client-controlled header/body bytes reach a trusted sink unsanitized",
    "TC15": "span/slot/in-flight registration not released on every exit path (incl. generator aclose)",
    "TC16": "flight/postmortem field not in the flight.py registries / ops path matched outside http11.ops_route",
    "TC17": "dispatch-site program kind unreachable from the warmup/AOT plan generators (mid-serve cold-compile hole)",
    "TC18": "KV page bytes spliced into a device pool without the registered tier-boundary pin check (verify_page_pin)",
    "TC19": "packed-KV write outside the byte-aligned helpers (pack_int4 -> buffer write, or hand-rolled nibble merge)",
    "TC20": "extracted KV page bytes reach a tunnel send / tier write / splice without verify_page_pin on every path (interprocedural)",
    "TC21": "client-controlled header/body bytes laundered through helper functions reach a trusted sink (interprocedural TC14)",
}


# ---------------------------------------------------------------------------
# Per-file result cache (ISSUE 18)
# ---------------------------------------------------------------------------

#: Entries kept before the oldest are evicted — a soft cap so an abandoned
#: cache dir cannot grow without bound across branch switches.
_CACHE_MAX_ENTRIES = 4096


def _rules_digest() -> str:
    """Content hash of every module in tools/tunnelcheck plus the Python
    version: editing ANY rule or substrate file invalidates the whole
    cache, which is what keeps the self-run-clean gate honest."""
    h = hashlib.blake2b(digest_size=16)
    h.update(f"py{sys.version_info[0]}.{sys.version_info[1]}".encode())
    pkg = Path(__file__).resolve().parent
    for f in sorted(pkg.glob("*.py")):
        h.update(f.name.encode())
        try:
            h.update(f.read_bytes())
        except OSError:
            h.update(b"<unreadable>")
    return h.hexdigest()


def _file_sha(path: Path) -> Optional[str]:
    try:
        return hashlib.blake2b(path.read_bytes(), digest_size=16).hexdigest()
    except OSError:
        return None


def _cache_base(scan: Sequence[Tuple[Path, Optional[str]]],
                selected_key: str) -> str:
    """Digest of the ENTIRE scanned tree (paths + content hashes) plus the
    rule modules and selected-rule set.  Interprocedural rules make
    per-file isolation unsound — a helper edited in one file changes the
    findings in its callers — so a single changed file invalidates every
    entry.  The warm run this accelerates is the common one: nothing
    changed since the last ``make lint``."""
    h = hashlib.blake2b(digest_size=16)
    h.update(_rules_digest().encode())
    h.update(selected_key.encode())
    for path, sha in scan:
        h.update(path.as_posix().encode())
        h.update((sha or "<unreadable>").encode())
    return h.hexdigest()


def _cache_entry_path(cache_dir: Path, base: str, path: Path,
                      sha: Optional[str]) -> Path:
    h = hashlib.blake2b(digest_size=16)
    h.update(base.encode())
    h.update(path.as_posix().encode())
    h.update((sha or "<unreadable>").encode())
    return cache_dir / f"{h.hexdigest()}.json"


def _violations_to_wire(violations: Iterable[Violation]) -> List[List]:
    return [[v.rule, v.line, v.end_line, v.message] for v in violations]


def _violations_from_wire(rows: Iterable[List], path: Path) -> List[Violation]:
    return [Violation(r[0], path, r[1], r[3], end_line=r[2]) for r in rows]


def _cache_write(cache_dir: Path, base: str,
                 scan: Sequence[Tuple[Path, Optional[str]]],
                 active: Sequence[Violation],
                 waived: Sequence[Violation]) -> None:
    try:
        cache_dir.mkdir(parents=True, exist_ok=True)
    except OSError:
        return
    by_path: Dict[str, Tuple[List[Violation], List[Violation]]] = {}
    for v in active:
        by_path.setdefault(str(v.path), ([], []))[0].append(v)
    for v in waived:
        by_path.setdefault(str(v.path), ([], []))[1].append(v)
    for path, sha in scan:
        a, w = by_path.get(str(path), ([], []))
        entry = {
            "path": path.as_posix(),
            "active": _violations_to_wire(a),
            "waived": _violations_to_wire(w),
        }
        target = _cache_entry_path(cache_dir, base, path, sha)
        try:
            tmp = target.with_suffix(".tmp")
            tmp.write_text(json.dumps(entry), encoding="utf-8")
            tmp.replace(target)
        except OSError:
            return
    try:
        entries = sorted(cache_dir.glob("*.json"),
                         key=lambda p: p.stat().st_mtime)
        for stale in entries[:-_CACHE_MAX_ENTRIES]:
            stale.unlink(missing_ok=True)
    except OSError:
        pass


def _cache_try(cache_dir: Path, base: str,
               scan: Sequence[Tuple[Path, Optional[str]]]
               ) -> Optional[Tuple[List[Violation], List[Violation]]]:
    """All-or-nothing warm load: every scanned file must have an entry
    under the current tree digest, or the run falls back to a cold pass.
    A hit skips parsing entirely — the waiver partition was computed from
    identical bytes, so replaying it is sound."""
    active: List[Violation] = []
    waived: List[Violation] = []
    for path, sha in scan:
        entry_path = _cache_entry_path(cache_dir, base, path, sha)
        try:
            entry = json.loads(entry_path.read_text(encoding="utf-8"))
        except (OSError, ValueError):
            return None
        active.extend(_violations_from_wire(entry.get("active", []), path))
        waived.extend(_violations_from_wire(entry.get("waived", []), path))
    return active, waived


# ---------------------------------------------------------------------------
# Waiver audit (ISSUE 18)
# ---------------------------------------------------------------------------

def audit_waivers(
    files: Sequence[SourceFile],
    waived: Sequence[Violation],
    selected: Sequence[str],
    full_run: bool,
) -> List[Tuple[Path, int, str]]:
    """Stale ``# tunnelcheck: disable=`` comments: waivers whose rule no
    longer fires on the statement they annotate.

    No second no-waiver pass is needed — ``run_paths`` already computes
    every violation and only *partitions* on waivers, so the ``waived``
    list IS the set of suppressions that earned their keep.  A line waiver
    for rule R is live iff some waived R-violation's statement span covers
    its line; a file waiver iff some waived R-violation exists in the
    file.  ``all`` waivers are only judged on full runs (a subset run
    cannot tell whether an unselected rule justifies them), and rule ids
    that do not exist are always reported — a typo'd waiver suppresses
    nothing and reads as if it did.
    """
    known = set(RULE_SUMMARIES)
    judged = set(selected)
    covered: Dict[Tuple[str, str], List[Tuple[int, int]]] = {}
    for v in waived:
        covered.setdefault((str(v.path), v.rule), []).append(
            (v.line, v.end_line or v.line)
        )
    out: List[Tuple[Path, int, str]] = []
    for sf in files:
        key_path = str(sf.path)

        def live(rule: str, line: Optional[int]) -> bool:
            rules = [rule] if rule != "all" else sorted(
                {r for (p, r) in covered if p == key_path}
            )
            for r in rules:
                for lo, hi in covered.get((key_path, r), ()):
                    if line is None or lo <= line <= hi:
                        return True
            return False

        for line in sorted(sf.line_waivers):
            for rule in sorted(sf.line_waivers[line]):
                if rule != "all" and rule not in known:
                    out.append((sf.path, line,
                                f"waiver names unknown rule `{rule}`"))
                    continue
                if rule == "all" and not full_run:
                    continue
                if rule != "all" and rule not in judged:
                    continue
                if not live(rule, line):
                    out.append((
                        sf.path, line,
                        f"stale waiver: `{rule}` no longer fires on this "
                        "statement — delete the comment",
                    ))
        for rule in sorted(sf.file_waivers):
            if rule != "all" and rule not in known:
                out.append((sf.path, 1,
                            f"file waiver names unknown rule `{rule}`"))
                continue
            if rule == "all" and not full_run:
                continue
            if rule != "all" and rule not in judged:
                continue
            if not live(rule, None):
                out.append((
                    sf.path, 1,
                    f"stale file waiver: `{rule}` fires nowhere in this "
                    "file — delete the comment",
                ))
    return out


#: Fork-inherited state for parallel workers: set by :func:`run_paths`
#: immediately before the pool forks, so child processes see the parsed
#: files and warmed ProjectContext via copy-on-write instead of re-parsing
#: the tree per worker.
_FORK_STATE: Optional[Tuple[List[SourceFile], ProjectContext, List[str]]] = None


def _check_one(
    sf: SourceFile, ctx: ProjectContext, selected: Sequence[str],
    checks: Dict[str, object],
) -> Tuple[List[Violation], List[Violation]]:
    active: List[Violation] = []
    waived: List[Violation] = []
    for rule_id in selected:
        for v in checks[rule_id](sf, ctx):
            (waived if sf.waived(v.rule, v.line, v.end_line) else active).append(v)
    return active, waived


def _fork_worker(indices: Sequence[int]) -> Tuple[List[Violation], List[Violation]]:
    files, ctx, selected = _FORK_STATE  # type: ignore[misc]
    checks = all_rules()
    active: List[Violation] = []
    waived: List[Violation] = []
    for i in indices:
        a, w = _check_one(files[i], ctx, selected, checks)
        active.extend(a)
        waived.extend(w)
    return active, waived


def _selected_rules(
    checks: Dict[str, object], rules: Optional[Sequence[str]]
) -> List[str]:
    if rules is None:
        return list(checks)
    # TC00 (parse errors) is always on; anything else unknown is a
    # caller bug — silently running zero rules would read as "clean".
    unknown = set(rules) - set(checks) - {"TC00"}
    if unknown:
        raise ValueError(
            f"unknown rule id(s): {', '.join(sorted(unknown))}"
        )
    return [r for r in rules if r in checks]


def run_paths(
    paths: Sequence[Path],
    rules: Optional[Sequence[str]] = None,
    stats: Optional[Dict[str, int]] = None,
    jobs: int = 1,
    restrict: Optional[Set[Path]] = None,
    cache_dir: Optional[Path] = None,
    waiver_audit: Optional[List[Tuple[Path, int, str]]] = None,
) -> Tuple[List[Violation], List[Violation]]:
    """Run the suite. Returns (active_violations, waived_violations).

    ``stats``, when given, receives ``{"files": <count scanned>}`` so the
    CLI summary doesn't re-walk the tree (plus ``cache_hits``/
    ``cache_misses`` when ``cache_dir`` is set).

    ``jobs`` > 1 fans the per-file rule passes across a fork-based
    multiprocessing pool (135 files × 15 rules is embarrassingly parallel;
    cross-file context is parsed once in the parent and inherited
    copy-on-write).  Platforms without fork fall back to serial — results
    are byte-identical either way, including TC00 parse errors, which are
    collected in the parent so the exit-code and summary paths can never
    disagree about them.

    ``restrict`` limits which files get *findings* (the ``--changed-only``
    mode) while the whole path set still feeds cross-file context — a
    changed-file scan must see the unchanged registries and callees or
    TC02/TC06/TC07 would lose their cross-file resolution.

    ``cache_dir`` enables the per-file result cache.  Keys include every
    file's content hash, the rule-module digest, and the whole-tree digest
    — with interprocedural rules a single edited helper changes findings
    in its callers, so any change invalidates everything (the honest
    all-or-nothing trade, documented in README).  A full hit skips the
    check phase entirely.  ``restrict`` runs bypass the cache.

    ``waiver_audit``, when a list, is filled with :func:`audit_waivers`
    results for the checked files.
    """
    scan: List[Tuple[Path, Optional[str]]] = []
    for path in iter_python_files(paths):
        scan.append((path, None))
    if stats is not None:
        stats["files"] = len(scan)

    checks = all_rules()
    selected = _selected_rules(checks, rules)
    full_run = rules is None

    use_cache = cache_dir is not None and restrict is None
    base = ""
    if use_cache:
        scan = [(p, _file_sha(p)) for p, _ in scan]
        base = _cache_base(scan, ",".join(selected))
        cached = _cache_try(cache_dir, base, scan)
        if cached is not None:
            active, waived = cached
            if stats is not None:
                stats["cache_hits"] = len(scan)
                stats["cache_misses"] = 0
            if waiver_audit is not None:
                warm_files = []
                for p, _sha in scan:
                    sf, _err = load_source(p)
                    if sf is not None:
                        warm_files.append(sf)
                waiver_audit.extend(
                    audit_waivers(warm_files, waived, selected, full_run)
                )
            active.sort(key=lambda v: (str(v.path), v.line, v.rule))
            waived.sort(key=lambda v: (str(v.path), v.line, v.rule))
            return active, waived
        if stats is not None:
            stats["cache_hits"] = 0
            stats["cache_misses"] = len(scan)

    files: List[SourceFile] = []
    active = []
    waived = []
    for path, _sha in scan:
        sf, err = load_source(path)
        if err is not None:
            if restrict is None or path.resolve() in restrict:
                active.append(err)
        if sf is not None:
            files.append(sf)

    ctx = ProjectContext(files)

    if restrict is None:
        check_files = files
    else:
        check_files = [sf for sf in files if sf.path.resolve() in restrict]

    ran_parallel = False
    if jobs > 1 and len(check_files) > 1:
        import multiprocessing

        try:
            mp = multiprocessing.get_context("fork")
        except ValueError:
            mp = None
        if mp is not None:
            # Warm the lazily-built shared structures BEFORE forking, so
            # every worker inherits them instead of rebuilding per process.
            ctx.callgraph
            ctx.attr_function_count("")
            for rule_id in selected:
                warm = getattr(checks[rule_id], "warm", None)
                if warm is not None:
                    warm(ctx)
            global _FORK_STATE
            file_index = {id(sf): i for i, sf in enumerate(files)}
            chunks: List[List[int]] = [[] for _ in range(jobs)]
            for j, sf in enumerate(check_files):
                chunks[j % jobs].append(file_index[id(sf)])
            chunks = [c for c in chunks if c]
            _FORK_STATE = (files, ctx, list(selected))
            try:
                with mp.Pool(len(chunks)) as pool:
                    for a, w in pool.map(_fork_worker, chunks):
                        active.extend(a)
                        waived.extend(w)
                ran_parallel = True
            finally:
                _FORK_STATE = None
    if not ran_parallel:
        for sf in check_files:
            a, w = _check_one(sf, ctx, selected, checks)
            active.extend(a)
            waived.extend(w)

    if use_cache:
        _cache_write(cache_dir, base, scan, active, waived)
    if waiver_audit is not None:
        waiver_audit.extend(
            audit_waivers(check_files, waived, selected, full_run)
        )

    active.sort(key=lambda v: (str(v.path), v.line, v.rule))
    waived.sort(key=lambda v: (str(v.path), v.line, v.rule))
    return active, waived
