"""Shared dataflow substrate: per-function CFGs, await-point partitioning,
reaching reads over ``self.*`` attributes, and a taint lattice.

Every serving-engine incident this repo shipped a fix for — the
x-tunnel-tenant minting hole (PR 7), the finish-recorded-after-final-yield
span leak (PR 6), the breaker half-open wedge (PR 8 review) — is a
*dataflow* bug: client-controlled bytes reaching a trusted sink, or shared
mutable state torn across an ``await``.  The 13 original rules each carried
a private sliver of flow analysis (TC07's transitive-dispatch closure,
TC03's traced-function marking); this module is the shared substrate the
incident-grounded rules (TC13/TC14/TC15) are built on, and that existing
rules migrate to via :mod:`tools.tunnelcheck.callgraph`.

Three layers, all stdlib-``ast`` (never importing the scanned code):

- :class:`FuncCFG` — basic blocks of :class:`Event` s with control-flow
  edges.  Statements are lowered to evaluation-order event streams (reads
  before writes, awaited operands before the suspension itself), so an
  ``AugAssign`` whose value awaits is correctly seen as read → await →
  write.  ``await`` and ``yield`` are both suspension events: an async
  generator parked at a ``yield`` has released the loop exactly like one
  parked at an ``await`` (and may never resume at all — ``aclose()``).
- :func:`attr_reach` — a forward worklist analysis over the CFG computing,
  at each write to a shared attribute, whether the value or the guarding
  read of that attribute crossed a suspension point (the await-atomicity
  question TC13 asks).  This is reaching-definitions with definitions
  replaced by *reads* and kill replaced by *refresh*: a re-read after the
  await (the check-again idiom) clears the crossed flag, because the code
  re-validated its premise.
- :func:`taint_locals` / :func:`expr_tainted` — a two-point taint lattice
  (clean < tainted) propagated through local assignments to a fixpoint.
  Sources and sanitizers are injected by the rule (TC14 seeds at
  client-controlled request headers/bodies and clears at the registered
  sanitizers), so the engine itself stays policy-free.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterable, Iterator, List, Optional, Set, Tuple

# ---------------------------------------------------------------------------
# Events
# ---------------------------------------------------------------------------

#: Mutating container/obj methods: calling one on a tracked attribute is a
#: WRITE to it (``self.departed.pop(pid)`` mutates shared state exactly as
#: ``self.departed = ...`` would, just in place).
MUTATING_METHODS = frozenset({
    "append", "extend", "insert", "remove", "pop", "popleft", "popitem",
    "clear", "update", "setdefault", "add", "discard", "appendleft",
})

#: Identifier words that mark an ``async with``/``with`` context expression
#: as a mutual-exclusion region (``self._lock``, ``self._admit_mutex``...).
LOCK_WORDS = frozenset({"lock", "mutex", "sem", "semaphore", "cond", "condition"})


@dataclass
class Event:
    """One atomicity-relevant action, in evaluation order.

    ``kind`` is one of:

    - ``read``    — load of a tracked attribute (``obj`` = root name)
    - ``write``   — store/mutation of a tracked attribute; ``deps`` names
                    the locals whose values flow into it
    - ``suspend`` — ``await`` or ``yield``/``yield from`` (``is_yield``
                    distinguishes them for messages)
    - ``local``   — assignment to a local name; ``deps`` = locals read by
                    the RHS, ``attr_deps`` = tracked attrs read by the RHS
    """

    kind: str
    line: int
    obj: str = ""
    attr: str = ""
    name: str = ""  # local target for kind="local"
    deps: Set[str] = field(default_factory=set)
    attr_deps: Set[Tuple[str, str]] = field(default_factory=set)
    locked: bool = False
    is_yield: bool = False
    node: Optional[ast.AST] = None


class Block:
    __slots__ = ("events", "succs")

    def __init__(self) -> None:
        self.events: List[Event] = []
        self.succs: List["Block"] = []

    def link(self, other: "Block") -> None:
        if other not in self.succs:
            self.succs.append(other)


def _attr_key(node: ast.AST) -> Optional[Tuple[str, str]]:
    """``(root, attr)`` for a one-level attribute access on a plain name
    (``self._x`` → ("self", "_x"), ``link.state`` → ("link", "state")).
    Deeper chains track their OUTERMOST shared hop (``self.a.b`` reads
    ``self.a``), which is what the atomicity question cares about."""
    while isinstance(node, ast.Attribute) and isinstance(node.value, ast.Attribute):
        node = node.value
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name):
        return (node.value.id, node.attr)
    return None


def _is_lockish(expr: ast.AST) -> bool:
    for sub in ast.walk(expr):
        name = None
        if isinstance(sub, ast.Attribute):
            name = sub.attr
        elif isinstance(sub, ast.Name):
            name = sub.id
        if name and LOCK_WORDS & set(name.lower().split("_")):
            return True
    return False


class _EventExtractor:
    """Lower one expression/statement to evaluation-order events."""

    def __init__(self, locked: bool):
        self.locked = locked
        self.out: List[Event] = []

    def _ev(self, kind: str, node: ast.AST, **kw) -> None:
        self.out.append(Event(
            kind, getattr(node, "lineno", 0), locked=self.locked,
            node=node, **kw,
        ))

    def expr(self, node: ast.AST) -> None:
        """Events of evaluating ``node``, children before the node's own
        effect (operands are evaluated before an await suspends, receivers
        before a mutating call fires)."""
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return  # nested scopes run later (or never); not this flow
        if isinstance(node, ast.Await):
            self.expr(node.value)
            self._ev("suspend", node)
            return
        if isinstance(node, (ast.Yield, ast.YieldFrom)):
            if getattr(node, "value", None) is not None:
                self.expr(node.value)
            self._ev("suspend", node, is_yield=True)
            return
        if isinstance(node, ast.Call):
            # Receiver/args first, then the call's own read/mutation.
            key = None
            method = ""
            if isinstance(node.func, ast.Attribute):
                key = _attr_key(node.func.value)
                method = node.func.attr
                self.expr(node.func.value)
            else:
                self.expr(node.func)
            for a in node.args:
                self.expr(a)
            for kw in node.keywords:
                self.expr(kw.value)
            if key is not None:
                if method in MUTATING_METHODS:
                    self._ev("write", node, obj=key[0], attr=key[1])
                else:
                    self._ev("read", node, obj=key[0], attr=key[1])
            return
        if isinstance(node, ast.Attribute):
            key = _attr_key(node)
            if key is not None:
                self._ev("read", node, obj=key[0], attr=key[1])
            else:
                self.expr(node.value)
            return
        for child in ast.iter_child_nodes(node):
            self.expr(child)

    def _value_deps(self, value: ast.AST) -> Tuple[Set[str], Set[Tuple[str, str]]]:
        names: Set[str] = set()
        attrs: Set[Tuple[str, str]] = set()
        for sub in ast.walk(value):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                continue
            if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Load):
                names.add(sub.id)
            key = _attr_key(sub) if isinstance(sub, ast.Attribute) else None
            if key is not None:
                attrs.add(key)
        return names, attrs

    def _store(self, target: ast.AST, value: Optional[ast.AST]) -> None:
        deps, attr_deps = self._value_deps(value) if value is not None else (set(), set())
        if isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self._store(e, value)
            return
        if isinstance(target, ast.Name):
            self._ev("local", target, name=target.id, deps=deps, attr_deps=attr_deps)
            return
        if isinstance(target, ast.Subscript):
            # ``self.reg[k] = v`` mutates ``self.reg`` in place.
            target = target.value
        key = _attr_key(target)
        if key is not None:
            self._ev("write", target, obj=key[0], attr=key[1],
                     deps=deps, attr_deps=attr_deps)

    def stmt(self, node: ast.stmt) -> None:
        if isinstance(node, ast.Assign):
            self.expr(node.value)
            for t in node.targets:
                self._store(t, node.value)
        elif isinstance(node, ast.AnnAssign):
            if node.value is not None:
                self.expr(node.value)
                self._store(node.target, node.value)
        elif isinstance(node, ast.AugAssign):
            # target is READ, then value evaluates (may suspend), then the
            # store — the exact torn-increment shape TC13 exists for.
            tkey = _attr_key(node.target)
            if tkey is not None:
                self._ev("read", node.target, obj=tkey[0], attr=tkey[1])
            self.expr(node.value)
            deps, attr_deps = self._value_deps(node.value)
            if tkey is not None:
                self._ev("write", node.target, obj=tkey[0], attr=tkey[1],
                         deps=deps, attr_deps=attr_deps | {tkey})
            elif isinstance(node.target, ast.Name):
                self._ev("local", node.target, name=node.target.id,
                         deps=deps | {node.target.id}, attr_deps=attr_deps)
            elif isinstance(node.target, ast.Subscript):
                self._store(node.target, node.value)
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                tgt = t.value if isinstance(t, ast.Subscript) else t
                key = _attr_key(tgt)
                if key is not None:
                    self._ev("write", t, obj=key[0], attr=key[1])
        elif isinstance(node, (ast.Expr, ast.Return, ast.Raise, ast.Assert)):
            for child in ast.iter_child_nodes(node):
                self.expr(child)
        else:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.expr):
                    self.expr(child)


# ---------------------------------------------------------------------------
# CFG construction
# ---------------------------------------------------------------------------

class FuncCFG:
    """Control-flow graph of one function body.

    ``entry``/``exit_block`` bracket the graph; ``blocks`` lists every
    reachable block.  Loops carry back edges; ``try`` bodies edge into
    their handlers from both the body's entry and its exit (the standard
    any-statement-may-raise approximation at block granularity); finally
    blocks are on every leaving path.  Nested function definitions are
    opaque — their bodies run in another activation, under their own CFG.
    """

    def __init__(self, fn: ast.AST):
        self.fn = fn
        self.entry = Block()
        self.exit_block = Block()
        self._loop_stack: List[Tuple[Block, Block]] = []  # (head, after)
        cur = self._build_body(list(fn.body), self.entry, locked=False)
        cur.link(self.exit_block)
        self.blocks = self._collect()

    # -- helpers ---------------------------------------------------------

    def _collect(self) -> List[Block]:
        seen: List[Block] = []
        stack = [self.entry]
        marked = {id(self.entry)}
        while stack:
            b = stack.pop()
            seen.append(b)
            for s in b.succs:
                if id(s) not in marked:
                    marked.add(id(s))
                    stack.append(s)
        return seen

    def _emit(self, stmt: ast.stmt, block: Block, locked: bool) -> None:
        ex = _EventExtractor(locked)
        ex.stmt(stmt)
        block.events.extend(ex.out)

    def _build_body(self, body: List[ast.stmt], cur: Block, locked: bool) -> Block:
        for stmt in body:
            cur = self._build_stmt(stmt, cur, locked)
        return cur

    def _build_stmt(self, stmt: ast.stmt, cur: Block, locked: bool) -> Block:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            return cur
        if isinstance(stmt, ast.If):
            ex = _EventExtractor(locked)
            ex.expr(stmt.test)
            cur.events.extend(ex.out)
            then_b, else_b, join = Block(), Block(), Block()
            cur.link(then_b)
            cur.link(else_b)
            self._build_body(stmt.body, then_b, locked).link(join)
            self._build_body(stmt.orelse, else_b, locked).link(join)
            return join
        if isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
            head, body_b, after = Block(), Block(), Block()
            cur.link(head)
            ex = _EventExtractor(locked)
            if isinstance(stmt, ast.While):
                ex.expr(stmt.test)
            else:
                ex.expr(stmt.iter)
                if isinstance(stmt, ast.AsyncFor):
                    # Each iteration awaits __anext__.
                    ex.out.append(Event("suspend", stmt.lineno, locked=locked))
                ex._store(stmt.target, None)
            head.events.extend(ex.out)
            head.link(body_b)
            head.link(after)
            self._loop_stack.append((head, after))
            self._build_body(stmt.body, body_b, locked).link(head)
            self._loop_stack.pop()
            if stmt.orelse:
                els = Block()
                head.link(els)
                self._build_body(stmt.orelse, els, locked).link(after)
            return after
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            lockish = any(_is_lockish(item.context_expr) for item in stmt.items)
            ex = _EventExtractor(locked)
            for item in stmt.items:
                ex.expr(item.context_expr)
                if isinstance(stmt, ast.AsyncWith):
                    ex.out.append(Event("suspend", stmt.lineno, locked=locked))
                if item.optional_vars is not None:
                    ex._store(item.optional_vars, item.context_expr)
            cur.events.extend(ex.out)
            return self._build_body(stmt.body, cur, locked or lockish)
        if isinstance(stmt, ast.Try):
            body_entry = Block()
            cur.link(body_entry)
            body_exit = self._build_body(stmt.body, body_entry, locked)
            else_exit = self._build_body(stmt.orelse, body_exit, locked)
            join = Block()
            handler_exits: List[Block] = [else_exit]
            for handler in stmt.handlers:
                h = Block()
                # Any statement in the body may raise: the handler sees
                # both the state at entry and the state at the end.
                body_entry.link(h)
                body_exit.link(h)
                handler_exits.append(self._build_body(handler.body, h, locked))
            if stmt.finalbody:
                fin = Block()
                for e in handler_exits:
                    e.link(fin)
                # The finally also runs on the raising/early-return paths.
                body_entry.link(fin)
                body_exit.link(fin)
                fin_exit = self._build_body(stmt.finalbody, fin, locked)
                fin_exit.link(join)
                fin_exit.link(self.exit_block)
            else:
                for e in handler_exits:
                    e.link(join)
            return join
        if isinstance(stmt, (ast.Return, ast.Raise)):
            self._emit(stmt, cur, locked)
            cur.link(self.exit_block)
            return Block()  # unreachable continuation
        if isinstance(stmt, (ast.Break, ast.Continue)):
            if self._loop_stack:
                head, after = self._loop_stack[-1]
                cur.link(after if isinstance(stmt, ast.Break) else head)
            return Block()
        self._emit(stmt, cur, locked)
        return cur


# ---------------------------------------------------------------------------
# Await-partitioned reaching reads (TC13's question)
# ---------------------------------------------------------------------------

@dataclass
class TornWrite:
    """A write to a shared attribute whose guarding read (or the value
    flowing into it) happened on the far side of a suspension point."""

    obj: str
    attr: str
    line: int
    suspend_line: int
    via_local: str = ""  # the stale local carrying the pre-suspend read
    is_yield: bool = False
    node: Optional[ast.AST] = None


def attr_reach(
    cfg: FuncCFG,
    tracked_roots: Set[str],
    tracked: Optional[Callable[[str, str], bool]] = None,
) -> List[TornWrite]:
    """Worklist fixpoint over ``cfg``: at each unlocked write to a tracked
    attribute, report whether the most recent read of that attribute — or
    a local whose value derives from such a read — crossed a suspension
    point since.  A re-read after the suspension *refreshes* the attribute
    (the check-again-after-await idiom is the sanctioned fix and must not
    flag); holding a lock around both sides suppresses entirely.
    """
    keep = tracked or (lambda obj, attr: True)

    # State: attr key -> (crossed, suspend_line, was_yield);
    # local -> {attr key -> same triple}.
    AttrState = Dict[Tuple[str, str], Tuple[bool, int, bool]]
    LocalState = Dict[str, Dict[Tuple[str, str], Tuple[bool, int, bool]]]

    def merge_attr(a: AttrState, b: AttrState) -> AttrState:
        out = dict(a)
        for k, v in b.items():
            if k in out:
                o = out[k]
                out[k] = (o[0] or v[0], max(o[1], v[1]), o[2] or v[2])
            else:
                out[k] = v
        return out

    def merge_local(a: LocalState, b: LocalState) -> LocalState:
        out = {k: dict(v) for k, v in a.items()}
        for name, deps in b.items():
            out[name] = merge_attr(out.get(name, {}), deps)
        return out

    in_attr: Dict[int, AttrState] = {id(cfg.entry): {}}
    in_local: Dict[int, LocalState] = {id(cfg.entry): {}}
    torn: Dict[Tuple[str, str, int], TornWrite] = {}

    worklist = [cfg.entry]
    iterations = 0
    limit = 4 * (len(cfg.blocks) + 1) * (len(cfg.blocks) + 8)
    while worklist and iterations < limit:
        iterations += 1
        block = worklist.pop()
        attrs: AttrState = dict(in_attr.get(id(block), {}))
        locals_: LocalState = {
            k: dict(v) for k, v in in_local.get(id(block), {}).items()
        }
        for ev in block.events:
            if ev.kind == "suspend":
                attrs = {
                    k: (True, ev.line, ev.is_yield) for k in attrs
                }
                locals_ = {
                    name: {k: (True, ev.line, ev.is_yield) for k in deps}
                    for name, deps in locals_.items()
                }
            elif ev.kind == "read":
                if ev.obj in tracked_roots and keep(ev.obj, ev.attr):
                    attrs[(ev.obj, ev.attr)] = (False, 0, False)
            elif ev.kind == "local":
                deps: Dict[Tuple[str, str], Tuple[bool, int, bool]] = {}
                for key in ev.attr_deps:
                    if key[0] in tracked_roots and keep(*key):
                        deps[key] = (False, 0, False)
                # sorted: Set iteration order is hash-seed-dependent, and
                # the reported line/local must be byte-identical between
                # the serial and forked runs.
                for dep in sorted(ev.deps):
                    for key, val in locals_.get(dep, {}).items():
                        cur = deps.get(key)
                        if cur is None or val[0] and not cur[0]:
                            deps[key] = val
                locals_[ev.name] = deps
            elif ev.kind == "write":
                key = (ev.obj, ev.attr)
                if ev.obj in tracked_roots and keep(ev.obj, ev.attr) \
                        and not ev.locked:
                    hit = None
                    via = ""
                    state = attrs.get(key)
                    if state is not None and state[0]:
                        hit = (state[1], state[2])
                    for dep in sorted(ev.deps):
                        val = locals_.get(dep, {}).get(key)
                        if val is not None and val[0]:
                            hit, via = (val[1], val[2]), dep
                            break
                    if hit is not None:
                        tk = (ev.obj, ev.attr, ev.line)
                        if tk not in torn:
                            torn[tk] = TornWrite(
                                ev.obj, ev.attr, ev.line, hit[0],
                                via_local=via, is_yield=hit[1], node=ev.node,
                            )
                # A write ENDS the RMW window whether or not it flagged:
                # the pending-read entry is dropped entirely, so a blind
                # write-after-write loop (keepalive stamping a timestamp
                # every interval) never reads as a read-modify-write.
                attrs.pop(key, None)
        for succ in block.succs:
            old_a = in_attr.get(id(succ))
            old_l = in_local.get(id(succ))
            new_a = attrs if old_a is None else merge_attr(old_a, attrs)
            new_l = locals_ if old_l is None else merge_local(old_l, locals_)
            if new_a != old_a or new_l != old_l:
                in_attr[id(succ)] = new_a
                in_local[id(succ)] = new_l
                if succ not in worklist:
                    worklist.append(succ)
    return sorted(torn.values(), key=lambda t: (t.line, t.attr))


# ---------------------------------------------------------------------------
# Attribute access index (the "reachable from two tasks" gate)
# ---------------------------------------------------------------------------

def suspension_lines(fn: ast.AST) -> List[int]:
    """Lines of every await/yield directly in ``fn`` (nested defs opaque)."""
    out: List[int] = []

    def walk(node: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                continue
            if isinstance(child, (ast.Await, ast.Yield, ast.YieldFrom)):
                out.append(getattr(child, "lineno", 0))
            walk(child)

    walk(fn)
    return out


def iter_functions(tree: ast.Module) -> Iterator[Tuple[ast.AST, Optional[str]]]:
    """Every (def, enclosing_class_name) in a module, any nesting."""

    def walk(node: ast.AST, cls: Optional[str]) -> Iterator[Tuple[ast.AST, Optional[str]]]:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                yield from walk(child, child.name)
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield child, cls
                yield from walk(child, None)
            else:
                yield from walk(child, cls)

    yield from walk(tree, None)


def attr_function_counts(trees: Iterable[ast.Module]) -> Dict[str, int]:
    """attr name -> number of distinct functions (project-wide) that read
    or write it through ANY receiver.  TC13's shared-state gate: an
    attribute only one function ever touches has a single-writer contract
    by construction and is exempt without a waiver."""
    counts: Dict[str, Set[int]] = {}
    for tree in trees:
        for fn, _cls in iter_functions(tree):
            fid = id(fn)
            for sub in ast.walk(fn):
                if isinstance(sub, ast.Attribute):
                    key = _attr_key(sub)
                    if key is not None:
                        counts.setdefault(key[1], set()).add(fid)
    return {attr: len(fns) for attr, fns in counts.items()}


# ---------------------------------------------------------------------------
# Taint lattice (TC14's engine)
# ---------------------------------------------------------------------------

def call_name(node: ast.Call) -> str:
    """Bare callee name of a call (``obj.meth(...)`` -> "meth") — shared by
    every rule that matches callees by name."""
    if isinstance(node.func, ast.Attribute):
        return node.func.attr
    if isinstance(node.func, ast.Name):
        return node.func.id
    return ""


def param_names(fn: ast.AST) -> Set[str]:
    """Named parameters of a def (positional-only + positional + kw-only)
    — the seed set taint/lifecycle/atomicity rules share."""
    a = fn.args
    return {x.arg for x in a.posonlyargs + a.args + a.kwonlyargs}


def expr_tainted(
    expr: ast.AST,
    tainted: Set[str],
    is_source: Callable[[ast.AST], bool],
    sanitizers: "frozenset[str] | Set[str]",
) -> bool:
    """Does evaluating ``expr`` yield client-controlled bytes?

    Tainted if any subexpression is a source or a tainted local, UNLESS
    the subexpression is (inside) a call to a registered sanitizer — the
    sanitizer's *result* is clean by definition, whatever it read.
    """
    sanitized: Set[int] = set()
    for sub in ast.walk(expr):
        if isinstance(sub, ast.Call) and call_name(sub) in sanitizers:
            sanitized.update(id(n) for n in ast.walk(sub))
    for sub in ast.walk(expr):
        if id(sub) in sanitized:
            continue
        if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        if is_source(sub):
            return True
        if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Load) \
                and sub.id in tainted:
            return True
    return False


def taint_locals(
    fn: ast.AST,
    is_source: Callable[[ast.AST], bool],
    sanitizers: "frozenset[str] | Set[str]",
    seed: Optional[Set[str]] = None,
) -> Set[str]:
    """Fixpoint of tainted local names in one function body.

    Flow-insensitive (a name tainted anywhere is tainted everywhere): this
    over-approximates, which for a security-ish rule is the right failure
    direction — the waiver syntax carries the human judgement.  Nested
    defs are opaque (their params rebind).
    """
    tainted: Set[str] = set(seed or ())

    def targets(node) -> Iterator[str]:
        tgts = node.targets if isinstance(node, ast.Assign) else [node.target]
        for t in tgts:
            if isinstance(t, ast.Name):
                yield t.id
            elif isinstance(t, (ast.Tuple, ast.List)):
                for e in t.elts:
                    if isinstance(e, ast.Name):
                        yield e.id

    stmts: List[ast.AST] = []

    def collect(node: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                continue
            stmts.append(child)
            collect(child)

    collect(fn)

    changed = True
    while changed:
        changed = False
        for node in stmts:
            if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                value = getattr(node, "value", None)
                if value is None:
                    continue
                if expr_tainted(value, tainted, is_source, sanitizers):
                    for name in targets(node):
                        if name not in tainted:
                            tainted.add(name)
                            changed = True
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                if expr_tainted(node.iter, tainted, is_source, sanitizers):
                    for t in ast.walk(node.target):
                        if isinstance(t, ast.Name) and t.id not in tainted:
                            tainted.add(t.id)
                            changed = True
            elif isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    if item.optional_vars is None:
                        continue
                    if expr_tainted(item.context_expr, tainted, is_source,
                                    sanitizers):
                        for t in ast.walk(item.optional_vars):
                            if isinstance(t, ast.Name) and t.id not in tainted:
                                tainted.add(t.id)
                                changed = True
    return tainted
