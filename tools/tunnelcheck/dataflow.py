"""Shared dataflow substrate: per-function CFGs, await-point partitioning,
reaching reads over ``self.*`` attributes, and a taint lattice.

Every serving-engine incident this repo shipped a fix for — the
x-tunnel-tenant minting hole (PR 7), the finish-recorded-after-final-yield
span leak (PR 6), the breaker half-open wedge (PR 8 review) — is a
*dataflow* bug: client-controlled bytes reaching a trusted sink, or shared
mutable state torn across an ``await``.  The 13 original rules each carried
a private sliver of flow analysis (TC07's transitive-dispatch closure,
TC03's traced-function marking); this module is the shared substrate the
incident-grounded rules (TC13/TC14/TC15) are built on, and that existing
rules migrate to via :mod:`tools.tunnelcheck.callgraph`.

Three layers, all stdlib-``ast`` (never importing the scanned code):

- :class:`FuncCFG` — basic blocks of :class:`Event` s with control-flow
  edges.  Statements are lowered to evaluation-order event streams (reads
  before writes, awaited operands before the suspension itself), so an
  ``AugAssign`` whose value awaits is correctly seen as read → await →
  write.  ``await`` and ``yield`` are both suspension events: an async
  generator parked at a ``yield`` has released the loop exactly like one
  parked at an ``await`` (and may never resume at all — ``aclose()``).
- :func:`attr_reach` — a forward worklist analysis over the CFG computing,
  at each write to a shared attribute, whether the value or the guarding
  read of that attribute crossed a suspension point (the await-atomicity
  question TC13 asks).  This is reaching-definitions with definitions
  replaced by *reads* and kill replaced by *refresh*: a re-read after the
  await (the check-again idiom) clears the crossed flag, because the code
  re-validated its premise.
- :func:`taint_locals` / :func:`expr_tainted` — a two-point taint lattice
  (clean < tainted) propagated through local assignments to a fixpoint.
  Sources and sanitizers are injected by the rule (TC14 seeds at
  client-controlled request headers/bodies and clears at the registered
  sanitizers), so the engine itself stays policy-free.
- :func:`interproc_taint` — the ISSUE 18 layer: per-function taint
  *summaries* (which params flow to the return value, which params reach
  a sink inside the function, whether the body taints its result from a
  source regardless of arguments) computed over the
  :mod:`~tools.tunnelcheck.callgraph` project graph and iterated to a
  fixpoint with a bounded number of rounds.  A page extracted in one
  helper and serialized in another — the exact shape the disaggregated
  prefill/decode and peer-KV-tier work will introduce — is invisible to
  every per-function rule; the summaries make the boundary crossing
  visible at the CALL SITE, where the waiver/fix belongs.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterable, Iterator, List, Optional, Set, Tuple

# ---------------------------------------------------------------------------
# Events
# ---------------------------------------------------------------------------

#: Mutating container/obj methods: calling one on a tracked attribute is a
#: WRITE to it (``self.departed.pop(pid)`` mutates shared state exactly as
#: ``self.departed = ...`` would, just in place).
MUTATING_METHODS = frozenset({
    "append", "extend", "insert", "remove", "pop", "popleft", "popitem",
    "clear", "update", "setdefault", "add", "discard", "appendleft",
})

#: Identifier words that mark an ``async with``/``with`` context expression
#: as a mutual-exclusion region (``self._lock``, ``self._admit_mutex``...).
LOCK_WORDS = frozenset({"lock", "mutex", "sem", "semaphore", "cond", "condition"})


@dataclass
class Event:
    """One atomicity-relevant action, in evaluation order.

    ``kind`` is one of:

    - ``read``    — load of a tracked attribute (``obj`` = root name)
    - ``write``   — store/mutation of a tracked attribute; ``deps`` names
                    the locals whose values flow into it
    - ``suspend`` — ``await`` or ``yield``/``yield from`` (``is_yield``
                    distinguishes them for messages)
    - ``local``   — assignment to a local name; ``deps`` = locals read by
                    the RHS, ``attr_deps`` = tracked attrs read by the RHS
    """

    kind: str
    line: int
    obj: str = ""
    attr: str = ""
    name: str = ""  # local target for kind="local"
    deps: Set[str] = field(default_factory=set)
    attr_deps: Set[Tuple[str, str]] = field(default_factory=set)
    locked: bool = False
    is_yield: bool = False
    node: Optional[ast.AST] = None


class Block:
    __slots__ = ("events", "succs")

    def __init__(self) -> None:
        self.events: List[Event] = []
        self.succs: List["Block"] = []

    def link(self, other: "Block") -> None:
        if other not in self.succs:
            self.succs.append(other)


def _attr_key(node: ast.AST) -> Optional[Tuple[str, str]]:
    """``(root, attr)`` for a one-level attribute access on a plain name
    (``self._x`` → ("self", "_x"), ``link.state`` → ("link", "state")).
    Deeper chains track their OUTERMOST shared hop (``self.a.b`` reads
    ``self.a``), which is what the atomicity question cares about."""
    while isinstance(node, ast.Attribute) and isinstance(node.value, ast.Attribute):
        node = node.value
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name):
        return (node.value.id, node.attr)
    return None


def _is_lockish(expr: ast.AST) -> bool:
    for sub in ast.walk(expr):
        name = None
        if isinstance(sub, ast.Attribute):
            name = sub.attr
        elif isinstance(sub, ast.Name):
            name = sub.id
        if name and LOCK_WORDS & set(name.lower().split("_")):
            return True
    return False


class _EventExtractor:
    """Lower one expression/statement to evaluation-order events."""

    def __init__(self, locked: bool):
        self.locked = locked
        self.out: List[Event] = []

    def _ev(self, kind: str, node: ast.AST, **kw) -> None:
        self.out.append(Event(
            kind, getattr(node, "lineno", 0), locked=self.locked,
            node=node, **kw,
        ))

    def expr(self, node: ast.AST) -> None:
        """Events of evaluating ``node``, children before the node's own
        effect (operands are evaluated before an await suspends, receivers
        before a mutating call fires)."""
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return  # nested scopes run later (or never); not this flow
        if isinstance(node, ast.Await):
            self.expr(node.value)
            self._ev("suspend", node)
            return
        if isinstance(node, (ast.Yield, ast.YieldFrom)):
            if getattr(node, "value", None) is not None:
                self.expr(node.value)
            self._ev("suspend", node, is_yield=True)
            return
        if isinstance(node, ast.Call):
            # Receiver/args first, then the call's own read/mutation.
            key = None
            method = ""
            if isinstance(node.func, ast.Attribute):
                key = _attr_key(node.func.value)
                method = node.func.attr
                self.expr(node.func.value)
            else:
                self.expr(node.func)
            for a in node.args:
                self.expr(a)
            for kw in node.keywords:
                self.expr(kw.value)
            if key is not None:
                if method in MUTATING_METHODS:
                    self._ev("write", node, obj=key[0], attr=key[1])
                else:
                    self._ev("read", node, obj=key[0], attr=key[1])
            return
        if isinstance(node, ast.Attribute):
            key = _attr_key(node)
            if key is not None:
                self._ev("read", node, obj=key[0], attr=key[1])
            else:
                self.expr(node.value)
            return
        for child in ast.iter_child_nodes(node):
            self.expr(child)

    def _value_deps(self, value: ast.AST) -> Tuple[Set[str], Set[Tuple[str, str]]]:
        names: Set[str] = set()
        attrs: Set[Tuple[str, str]] = set()
        for sub in ast.walk(value):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                continue
            if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Load):
                names.add(sub.id)
            key = _attr_key(sub) if isinstance(sub, ast.Attribute) else None
            if key is not None:
                attrs.add(key)
        return names, attrs

    def _store(self, target: ast.AST, value: Optional[ast.AST]) -> None:
        deps, attr_deps = self._value_deps(value) if value is not None else (set(), set())
        if isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self._store(e, value)
            return
        if isinstance(target, ast.Name):
            self._ev("local", target, name=target.id, deps=deps, attr_deps=attr_deps)
            return
        if isinstance(target, ast.Subscript):
            # ``self.reg[k] = v`` mutates ``self.reg`` in place.
            target = target.value
        key = _attr_key(target)
        if key is not None:
            self._ev("write", target, obj=key[0], attr=key[1],
                     deps=deps, attr_deps=attr_deps)

    def stmt(self, node: ast.stmt) -> None:
        if isinstance(node, ast.Assign):
            self.expr(node.value)
            for t in node.targets:
                self._store(t, node.value)
        elif isinstance(node, ast.AnnAssign):
            if node.value is not None:
                self.expr(node.value)
                self._store(node.target, node.value)
        elif isinstance(node, ast.AugAssign):
            # target is READ, then value evaluates (may suspend), then the
            # store — the exact torn-increment shape TC13 exists for.
            tkey = _attr_key(node.target)
            if tkey is not None:
                self._ev("read", node.target, obj=tkey[0], attr=tkey[1])
            self.expr(node.value)
            deps, attr_deps = self._value_deps(node.value)
            if tkey is not None:
                self._ev("write", node.target, obj=tkey[0], attr=tkey[1],
                         deps=deps, attr_deps=attr_deps | {tkey})
            elif isinstance(node.target, ast.Name):
                self._ev("local", node.target, name=node.target.id,
                         deps=deps | {node.target.id}, attr_deps=attr_deps)
            elif isinstance(node.target, ast.Subscript):
                self._store(node.target, node.value)
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                tgt = t.value if isinstance(t, ast.Subscript) else t
                key = _attr_key(tgt)
                if key is not None:
                    self._ev("write", t, obj=key[0], attr=key[1])
        elif isinstance(node, (ast.Expr, ast.Return, ast.Raise, ast.Assert)):
            for child in ast.iter_child_nodes(node):
                self.expr(child)
        else:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.expr):
                    self.expr(child)


# ---------------------------------------------------------------------------
# CFG construction
# ---------------------------------------------------------------------------

class FuncCFG:
    """Control-flow graph of one function body.

    ``entry``/``exit_block`` bracket the graph; ``blocks`` lists every
    reachable block.  Loops carry back edges; ``try`` bodies edge into
    their handlers from both the body's entry and its exit (the standard
    any-statement-may-raise approximation at block granularity); finally
    blocks are on every leaving path.  Nested function definitions are
    opaque — their bodies run in another activation, under their own CFG.
    """

    def __init__(self, fn: ast.AST):
        self.fn = fn
        self.entry = Block()
        self.exit_block = Block()
        self._loop_stack: List[Tuple[Block, Block]] = []  # (head, after)
        cur = self._build_body(list(fn.body), self.entry, locked=False)
        cur.link(self.exit_block)
        self.blocks = self._collect()

    # -- helpers ---------------------------------------------------------

    def _collect(self) -> List[Block]:
        seen: List[Block] = []
        stack = [self.entry]
        marked = {id(self.entry)}
        while stack:
            b = stack.pop()
            seen.append(b)
            for s in b.succs:
                if id(s) not in marked:
                    marked.add(id(s))
                    stack.append(s)
        return seen

    def _emit(self, stmt: ast.stmt, block: Block, locked: bool) -> None:
        ex = _EventExtractor(locked)
        ex.stmt(stmt)
        block.events.extend(ex.out)

    def _build_body(self, body: List[ast.stmt], cur: Block, locked: bool) -> Block:
        for stmt in body:
            cur = self._build_stmt(stmt, cur, locked)
        return cur

    def _build_stmt(self, stmt: ast.stmt, cur: Block, locked: bool) -> Block:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            return cur
        if isinstance(stmt, ast.If):
            ex = _EventExtractor(locked)
            ex.expr(stmt.test)
            cur.events.extend(ex.out)
            then_b, else_b, join = Block(), Block(), Block()
            cur.link(then_b)
            cur.link(else_b)
            self._build_body(stmt.body, then_b, locked).link(join)
            self._build_body(stmt.orelse, else_b, locked).link(join)
            return join
        if isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
            head, body_b, after = Block(), Block(), Block()
            cur.link(head)
            ex = _EventExtractor(locked)
            if isinstance(stmt, ast.While):
                ex.expr(stmt.test)
            else:
                ex.expr(stmt.iter)
                if isinstance(stmt, ast.AsyncFor):
                    # Each iteration awaits __anext__.
                    ex.out.append(Event("suspend", stmt.lineno, locked=locked))
                ex._store(stmt.target, None)
            head.events.extend(ex.out)
            head.link(body_b)
            head.link(after)
            self._loop_stack.append((head, after))
            self._build_body(stmt.body, body_b, locked).link(head)
            self._loop_stack.pop()
            if stmt.orelse:
                els = Block()
                head.link(els)
                self._build_body(stmt.orelse, els, locked).link(after)
            return after
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            lockish = any(_is_lockish(item.context_expr) for item in stmt.items)
            ex = _EventExtractor(locked)
            for item in stmt.items:
                ex.expr(item.context_expr)
                if isinstance(stmt, ast.AsyncWith):
                    ex.out.append(Event("suspend", stmt.lineno, locked=locked))
                if item.optional_vars is not None:
                    ex._store(item.optional_vars, item.context_expr)
            cur.events.extend(ex.out)
            return self._build_body(stmt.body, cur, locked or lockish)
        if isinstance(stmt, ast.Try):
            body_entry = Block()
            cur.link(body_entry)
            body_exit = self._build_body(stmt.body, body_entry, locked)
            else_exit = self._build_body(stmt.orelse, body_exit, locked)
            join = Block()
            handler_exits: List[Block] = [else_exit]
            for handler in stmt.handlers:
                h = Block()
                # Any statement in the body may raise: the handler sees
                # both the state at entry and the state at the end.
                body_entry.link(h)
                body_exit.link(h)
                handler_exits.append(self._build_body(handler.body, h, locked))
            if stmt.finalbody:
                fin = Block()
                for e in handler_exits:
                    e.link(fin)
                # The finally also runs on the raising/early-return paths.
                body_entry.link(fin)
                body_exit.link(fin)
                fin_exit = self._build_body(stmt.finalbody, fin, locked)
                fin_exit.link(join)
                fin_exit.link(self.exit_block)
            else:
                for e in handler_exits:
                    e.link(join)
            return join
        if isinstance(stmt, (ast.Return, ast.Raise)):
            self._emit(stmt, cur, locked)
            cur.link(self.exit_block)
            return Block()  # unreachable continuation
        if isinstance(stmt, (ast.Break, ast.Continue)):
            if self._loop_stack:
                head, after = self._loop_stack[-1]
                cur.link(after if isinstance(stmt, ast.Break) else head)
            return Block()
        self._emit(stmt, cur, locked)
        return cur


# ---------------------------------------------------------------------------
# Await-partitioned reaching reads (TC13's question)
# ---------------------------------------------------------------------------

@dataclass
class TornWrite:
    """A write to a shared attribute whose guarding read (or the value
    flowing into it) happened on the far side of a suspension point."""

    obj: str
    attr: str
    line: int
    suspend_line: int
    via_local: str = ""  # the stale local carrying the pre-suspend read
    is_yield: bool = False
    node: Optional[ast.AST] = None


def attr_reach(
    cfg: FuncCFG,
    tracked_roots: Set[str],
    tracked: Optional[Callable[[str, str], bool]] = None,
) -> List[TornWrite]:
    """Worklist fixpoint over ``cfg``: at each unlocked write to a tracked
    attribute, report whether the most recent read of that attribute — or
    a local whose value derives from such a read — crossed a suspension
    point since.  A re-read after the suspension *refreshes* the attribute
    (the check-again-after-await idiom is the sanctioned fix and must not
    flag); holding a lock around both sides suppresses entirely.
    """
    keep = tracked or (lambda obj, attr: True)

    # State: attr key -> (crossed, suspend_line, was_yield);
    # local -> {attr key -> same triple}.
    AttrState = Dict[Tuple[str, str], Tuple[bool, int, bool]]
    LocalState = Dict[str, Dict[Tuple[str, str], Tuple[bool, int, bool]]]

    def merge_attr(a: AttrState, b: AttrState) -> AttrState:
        out = dict(a)
        for k, v in b.items():
            if k in out:
                o = out[k]
                out[k] = (o[0] or v[0], max(o[1], v[1]), o[2] or v[2])
            else:
                out[k] = v
        return out

    def merge_local(a: LocalState, b: LocalState) -> LocalState:
        out = {k: dict(v) for k, v in a.items()}
        for name, deps in b.items():
            out[name] = merge_attr(out.get(name, {}), deps)
        return out

    in_attr: Dict[int, AttrState] = {id(cfg.entry): {}}
    in_local: Dict[int, LocalState] = {id(cfg.entry): {}}
    torn: Dict[Tuple[str, str, int], TornWrite] = {}

    worklist = [cfg.entry]
    iterations = 0
    limit = 4 * (len(cfg.blocks) + 1) * (len(cfg.blocks) + 8)
    while worklist and iterations < limit:
        iterations += 1
        block = worklist.pop()
        attrs: AttrState = dict(in_attr.get(id(block), {}))
        locals_: LocalState = {
            k: dict(v) for k, v in in_local.get(id(block), {}).items()
        }
        for ev in block.events:
            if ev.kind == "suspend":
                attrs = {
                    k: (True, ev.line, ev.is_yield) for k in attrs
                }
                locals_ = {
                    name: {k: (True, ev.line, ev.is_yield) for k in deps}
                    for name, deps in locals_.items()
                }
            elif ev.kind == "read":
                if ev.obj in tracked_roots and keep(ev.obj, ev.attr):
                    attrs[(ev.obj, ev.attr)] = (False, 0, False)
            elif ev.kind == "local":
                deps: Dict[Tuple[str, str], Tuple[bool, int, bool]] = {}
                for key in ev.attr_deps:
                    if key[0] in tracked_roots and keep(*key):
                        deps[key] = (False, 0, False)
                # sorted: Set iteration order is hash-seed-dependent, and
                # the reported line/local must be byte-identical between
                # the serial and forked runs.
                for dep in sorted(ev.deps):
                    for key, val in locals_.get(dep, {}).items():
                        cur = deps.get(key)
                        if cur is None or val[0] and not cur[0]:
                            deps[key] = val
                locals_[ev.name] = deps
            elif ev.kind == "write":
                key = (ev.obj, ev.attr)
                if ev.obj in tracked_roots and keep(ev.obj, ev.attr) \
                        and not ev.locked:
                    hit = None
                    via = ""
                    state = attrs.get(key)
                    if state is not None and state[0]:
                        hit = (state[1], state[2])
                    for dep in sorted(ev.deps):
                        val = locals_.get(dep, {}).get(key)
                        if val is not None and val[0]:
                            hit, via = (val[1], val[2]), dep
                            break
                    if hit is not None:
                        tk = (ev.obj, ev.attr, ev.line)
                        if tk not in torn:
                            torn[tk] = TornWrite(
                                ev.obj, ev.attr, ev.line, hit[0],
                                via_local=via, is_yield=hit[1], node=ev.node,
                            )
                # A write ENDS the RMW window whether or not it flagged:
                # the pending-read entry is dropped entirely, so a blind
                # write-after-write loop (keepalive stamping a timestamp
                # every interval) never reads as a read-modify-write.
                attrs.pop(key, None)
        for succ in block.succs:
            old_a = in_attr.get(id(succ))
            old_l = in_local.get(id(succ))
            new_a = attrs if old_a is None else merge_attr(old_a, attrs)
            new_l = locals_ if old_l is None else merge_local(old_l, locals_)
            if new_a != old_a or new_l != old_l:
                in_attr[id(succ)] = new_a
                in_local[id(succ)] = new_l
                if succ not in worklist:
                    worklist.append(succ)
    return sorted(torn.values(), key=lambda t: (t.line, t.attr))


# ---------------------------------------------------------------------------
# Attribute access index (the "reachable from two tasks" gate)
# ---------------------------------------------------------------------------

def suspension_lines(fn: ast.AST) -> List[int]:
    """Lines of every await/yield directly in ``fn`` (nested defs opaque)."""
    out: List[int] = []

    def walk(node: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                continue
            if isinstance(child, (ast.Await, ast.Yield, ast.YieldFrom)):
                out.append(getattr(child, "lineno", 0))
            walk(child)

    walk(fn)
    return out


def iter_functions(tree: ast.Module) -> Iterator[Tuple[ast.AST, Optional[str]]]:
    """Every (def, enclosing_class_name) in a module, any nesting."""

    def walk(node: ast.AST, cls: Optional[str]) -> Iterator[Tuple[ast.AST, Optional[str]]]:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                yield from walk(child, child.name)
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield child, cls
                yield from walk(child, None)
            else:
                yield from walk(child, cls)

    yield from walk(tree, None)


def attr_function_counts(trees: Iterable[ast.Module]) -> Dict[str, int]:
    """attr name -> number of distinct functions (project-wide) that read
    or write it through ANY receiver.  TC13's shared-state gate: an
    attribute only one function ever touches has a single-writer contract
    by construction and is exempt without a waiver."""
    counts: Dict[str, Set[int]] = {}
    for tree in trees:
        for fn, _cls in iter_functions(tree):
            fid = id(fn)
            for sub in ast.walk(fn):
                if isinstance(sub, ast.Attribute):
                    key = _attr_key(sub)
                    if key is not None:
                        counts.setdefault(key[1], set()).add(fid)
    return {attr: len(fns) for attr, fns in counts.items()}


# ---------------------------------------------------------------------------
# Taint lattice (TC14's engine)
# ---------------------------------------------------------------------------

def call_name(node: ast.Call) -> str:
    """Bare callee name of a call (``obj.meth(...)`` -> "meth") — shared by
    every rule that matches callees by name."""
    if isinstance(node.func, ast.Attribute):
        return node.func.attr
    if isinstance(node.func, ast.Name):
        return node.func.id
    return ""


def param_names(fn: ast.AST) -> Set[str]:
    """Named parameters of a def (positional-only + positional + kw-only)
    — the seed set taint/lifecycle/atomicity rules share."""
    a = fn.args
    return {x.arg for x in a.posonlyargs + a.args + a.kwonlyargs}


def expr_tainted(
    expr: ast.AST,
    tainted: Set[str],
    is_source: Callable[[ast.AST], bool],
    sanitizers: "frozenset[str] | Set[str]",
) -> bool:
    """Does evaluating ``expr`` yield client-controlled bytes?

    Tainted if any subexpression is a source or a tainted local, UNLESS
    the subexpression is (inside) a call to a registered sanitizer — the
    sanitizer's *result* is clean by definition, whatever it read.
    """
    sanitized: Set[int] = set()
    for sub in ast.walk(expr):
        if isinstance(sub, ast.Call) and call_name(sub) in sanitizers:
            sanitized.update(id(n) for n in ast.walk(sub))
    for sub in ast.walk(expr):
        if id(sub) in sanitized:
            continue
        if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        if is_source(sub):
            return True
        if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Load) \
                and sub.id in tainted:
            return True
    return False


def taint_locals(
    fn: ast.AST,
    is_source: Callable[[ast.AST], bool],
    sanitizers: "frozenset[str] | Set[str]",
    seed: Optional[Set[str]] = None,
) -> Set[str]:
    """Fixpoint of tainted local names in one function body.

    Flow-insensitive (a name tainted anywhere is tainted everywhere): this
    over-approximates, which for a security-ish rule is the right failure
    direction — the waiver syntax carries the human judgement.  Nested
    defs are opaque (their params rebind).
    """
    tainted: Set[str] = set(seed or ())

    def targets(node) -> Iterator[str]:
        tgts = node.targets if isinstance(node, ast.Assign) else [node.target]
        for t in tgts:
            if isinstance(t, ast.Name):
                yield t.id
            elif isinstance(t, (ast.Tuple, ast.List)):
                for e in t.elts:
                    if isinstance(e, ast.Name):
                        yield e.id

    stmts: List[ast.AST] = []

    def collect(node: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                continue
            stmts.append(child)
            collect(child)

    collect(fn)

    changed = True
    while changed:
        changed = False
        for node in stmts:
            if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                value = getattr(node, "value", None)
                if value is None:
                    continue
                if expr_tainted(value, tainted, is_source, sanitizers):
                    for name in targets(node):
                        if name not in tainted:
                            tainted.add(name)
                            changed = True
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                if expr_tainted(node.iter, tainted, is_source, sanitizers):
                    for t in ast.walk(node.target):
                        if isinstance(t, ast.Name) and t.id not in tainted:
                            tainted.add(t.id)
                            changed = True
            elif isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    if item.optional_vars is None:
                        continue
                    if expr_tainted(item.context_expr, tainted, is_source,
                                    sanitizers):
                        for t in ast.walk(item.optional_vars):
                            if isinstance(t, ast.Name) and t.id not in tainted:
                                tainted.add(t.id)
                                changed = True
    return tainted


# ---------------------------------------------------------------------------
# Interprocedural taint summaries (TC20/TC21's engine)
# ---------------------------------------------------------------------------

#: Label meaning "a source was observed on this path" — distinct from the
#: param-name labels so one pass computes both the param→return transfer
#: (which arguments contaminate my result?) and the always-tainted case
#: (my body reads a source no matter what callers pass).
SRC = "<src>"

#: Passes over a loop body before declaring the loop state stable.  Labels
#: only ever accumulate inside a pass, so pass k sees everything a chain of
#: k intra-loop assignments can carry; deeper chains through a back edge
#: are vanishingly rare in review-scale code and the cap keeps the walker
#: linear on the pathological inputs the checker must never hang on.
LOOP_PASSES = 4


def walk_same_scope(node: ast.AST) -> Iterator[ast.AST]:
    """``ast.walk`` that treats nested defs/lambdas as opaque — their
    bodies run in another activation (or never), not in this flow."""
    stack = [node]
    while stack:
        cur = stack.pop()
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        yield cur
        stack.extend(ast.iter_child_nodes(cur))


@dataclass
class TaintPolicy:
    """What a rule injects into the interprocedural engine.

    ``is_source`` / ``sanitizers`` mirror :func:`taint_locals`.
    ``seed_params`` are parameter names presumed hostile at *reporting*
    time only (public entry points whose callers live outside the scanned
    universe); summaries are never seeded, so a helper taking a ``payload``
    argument stays exactly as trustworthy as what each call site passes.
    ``sink_args`` maps a call to ``(argument expression, sink description)``
    pairs the rule wants judged; ``sink_assign`` does the same for
    assignment statements (subscript-store sinks like ``kwargs["tenant"]``).
    """

    is_source: Callable[[ast.AST], bool]
    sanitizers: "frozenset[str] | Set[str]"
    seed_params: "frozenset[str] | Set[str]" = frozenset()
    sink_args: Optional[
        Callable[[ast.Call], List[Tuple[ast.AST, str]]]
    ] = None
    sink_assign: Optional[
        Callable[[ast.Assign], List[Tuple[ast.AST, str]]]
    ] = None


@dataclass
class FuncSummary:
    """One function's taint behaviour as seen from a call site.

    ``ret`` — labels reaching a ``return``/``yield`` value: parameter
    names (the result is as dirty as that argument) and/or :data:`SRC`
    (the body taints its result unconditionally).  ``sink_params`` —
    parameter name → description of the sink it can reach inside the
    function (transitively, via callee summaries) without passing a
    sanitizer on that path.
    """

    ret: Set[str] = field(default_factory=set)
    sink_params: Dict[str, str] = field(default_factory=dict)


def _copy_env(env: Optional[Dict[str, Set[str]]]) -> Optional[Dict[str, Set[str]]]:
    if env is None:
        return None
    return {k: set(v) for k, v in env.items()}


def _join_env(
    a: Optional[Dict[str, Set[str]]], b: Optional[Dict[str, Set[str]]]
) -> Optional[Dict[str, Set[str]]]:
    """Path join: ``None`` means "all paths left the scope" and is the
    identity; otherwise key-wise label union (may-taint)."""
    if a is None:
        return _copy_env(b)
    if b is None:
        return a
    out = {k: set(v) for k, v in a.items()}
    for k, v in b.items():
        out.setdefault(k, set()).update(v)
    return out


def map_call_args(call: ast.Call, info) -> Dict[str, ast.AST]:
    """Best-effort argument-expression-per-parameter map for a call against
    a :class:`~tools.tunnelcheck.core.FuncInfo` signature.  A method called
    through an attribute binds the receiver to its first parameter;
    positions after a ``*args`` splat are unknowable and dropped (the
    engine falls back to judging splatted values conservatively)."""
    mapped: Dict[str, ast.AST] = {}
    drop_self = info.is_method and isinstance(call.func, ast.Attribute)
    pos = info.effective_pos(drop_self)
    if drop_self and info.pos:
        mapped[info.pos[0]] = call.func.value
    for i, a in enumerate(call.args):
        if isinstance(a, ast.Starred):
            break
        if i < len(pos):
            mapped[pos[i]] = a
    for kw in call.keywords:
        if kw.arg:
            mapped[kw.arg] = kw.value
    return mapped


class _SummaryFlow:
    """Flow-sensitive label propagation over one function body.

    The environment maps local name → label set; ``None`` means every path
    left the scope.  A whole-name reassignment from clean values KILLS the
    taint (that is what makes ``payload = verify_page_pin(payload, ...)``
    the sanctioned idiom), while subscript stores, ``AugAssign`` and
    mutating-method calls only ever ADD labels — mutating part of a
    container never launders the rest of it.
    """

    def __init__(self, engine: "InterprocTaint", fn: ast.AST,
                 summary: FuncSummary,
                 on_sink: Optional[Callable[[ast.AST, str], None]]):
        self.engine = engine
        self.policy = engine.policy
        self.fn = fn
        self.params = param_names(fn)
        self.summary = summary
        self.on_sink = on_sink
        self._breaks: List[List[Optional[Dict[str, Set[str]]]]] = []
        self._continues: List[List[Optional[Dict[str, Set[str]]]]] = []

    def run(self) -> FuncSummary:
        env: Dict[str, Set[str]] = {p: {p} for p in self.params}
        if self.on_sink is not None:
            for p in self.params & set(self.policy.seed_params):
                env[p].add(SRC)
        self.run_body(list(self.fn.body), env)
        return self.summary

    # -- label evaluation -------------------------------------------------

    def eval(self, expr: Optional[ast.AST],
             env: Dict[str, Set[str]]) -> Set[str]:
        if expr is None:
            return set()
        out: Set[str] = set()
        if isinstance(expr, ast.Name):
            if isinstance(expr.ctx, ast.Load):
                out |= env.get(expr.id, set())
        elif isinstance(expr, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.Lambda)):
            return set()
        elif isinstance(expr, ast.Call):
            name = call_name(expr)
            if name in self.policy.sanitizers:
                return set()
            if isinstance(expr.func, ast.Attribute):
                # A method result on a tainted receiver stays tainted
                # (``page.copy()``, ``payload.items()``).
                out |= self.eval(expr.func.value, env)
            resolved = self.engine._callee(name) if name else None
            if resolved is not None:
                info, summary = resolved
                if SRC in summary.ret:
                    out.add(SRC)
                mapped = map_call_args(expr, info)
                for p in summary.ret - {SRC}:
                    arg = mapped.get(p)
                    if arg is not None:
                        out |= self.eval(arg, env)
                for a in expr.args:
                    if isinstance(a, ast.Starred):
                        out |= self.eval(a.value, env)
                for kw in expr.keywords:
                    if kw.arg is None:
                        out |= self.eval(kw.value, env)
            else:
                for a in expr.args:
                    out |= self.eval(
                        a.value if isinstance(a, ast.Starred) else a, env)
                for kw in expr.keywords:
                    out |= self.eval(kw.value, env)
        elif isinstance(expr, ast.Attribute):
            out |= self.eval(expr.value, env)
        else:
            for child in ast.iter_child_nodes(expr):
                if isinstance(child, ast.expr):
                    out |= self.eval(child, env)
                elif isinstance(child, ast.comprehension):
                    out |= self.eval(child.iter, env)
                elif isinstance(child, ast.keyword):
                    out |= self.eval(child.value, env)
        if self.policy.is_source(expr):
            out.add(SRC)
        return out

    # -- sink / mutation scan ---------------------------------------------

    def _hit(self, node: ast.AST, desc: str, labels: Set[str]) -> None:
        if not labels:
            return
        if SRC in labels and self.on_sink is not None:
            self.on_sink(node, desc)
        for p in labels & self.params:
            self.summary.sink_params.setdefault(p, desc)

    def scan(self, expr: Optional[ast.AST], env: Dict[str, Set[str]]) -> None:
        """Judge every call in ``expr`` against the policy's intrinsic
        sinks and against callee summaries, and apply container-mutation
        taint (``out.append(page)`` dirties ``out``)."""
        if expr is None:
            return
        for sub in walk_same_scope(expr):
            if isinstance(sub, (ast.Yield, ast.YieldFrom)):
                if sub.value is not None:
                    self.summary.ret |= self.eval(sub.value, env)
                continue
            if not isinstance(sub, ast.Call):
                continue
            if isinstance(sub.func, ast.Attribute) \
                    and sub.func.attr in MUTATING_METHODS \
                    and isinstance(sub.func.value, ast.Name):
                labels: Set[str] = set()
                for a in sub.args:
                    labels |= self.eval(
                        a.value if isinstance(a, ast.Starred) else a, env)
                for kw in sub.keywords:
                    labels |= self.eval(kw.value, env)
                if labels:
                    env.setdefault(sub.func.value.id, set()).update(labels)
            if self.policy.sink_args is not None:
                for arg, desc in self.policy.sink_args(sub):
                    self._hit(sub, desc, self.eval(arg, env))
            name = call_name(sub)
            if name and name not in self.policy.sanitizers:
                resolved = self.engine._callee(name)
                if resolved is not None:
                    info, summary = resolved
                    if summary.sink_params:
                        mapped = map_call_args(sub, info)
                        for p, desc in sorted(summary.sink_params.items()):
                            arg = mapped.get(p)
                            if arg is not None:
                                self._hit(sub, f"{desc} via `{info.name}()`",
                                          self.eval(arg, env))

    # -- statements -------------------------------------------------------

    def assign(self, target: ast.AST, labels: Set[str],
               env: Dict[str, Set[str]]) -> None:
        if isinstance(target, ast.Name):
            env[target.id] = set(labels)
        elif isinstance(target, ast.Starred):
            self.assign(target.value, labels, env)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self.assign(e, labels, env)
        elif isinstance(target, ast.Subscript):
            base = target.value
            if isinstance(base, ast.Name) and labels:
                env.setdefault(base.id, set()).update(labels)
        # Attribute stores are out of scope: cross-attribute flow belongs
        # to attr_reach/TC13, and tracking it here would make summaries
        # depend on object identity the name-keyed graph cannot see.

    def run_body(self, body: List[ast.stmt],
                 env: Optional[Dict[str, Set[str]]]
                 ) -> Optional[Dict[str, Set[str]]]:
        for stmt in body:
            if env is None:
                return None
            env = self.stmt(stmt, env)
        return env

    def stmt(self, node: ast.stmt,
             env: Dict[str, Set[str]]) -> Optional[Dict[str, Set[str]]]:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return env
        if isinstance(node, ast.Return):
            if node.value is not None:
                self.scan(node.value, env)
                self.summary.ret |= self.eval(node.value, env)
            return None
        if isinstance(node, ast.Raise):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.expr):
                    self.scan(child, env)
            return None
        if isinstance(node, ast.Break):
            if self._breaks:
                self._breaks[-1].append(_copy_env(env))
            return None
        if isinstance(node, ast.Continue):
            if self._continues:
                self._continues[-1].append(_copy_env(env))
            return None
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            value = node.value
            if value is None:
                return env
            self.scan(value, env)
            labels = self.eval(value, env)
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                self.assign(t, labels, env)
            if isinstance(node, ast.Assign) \
                    and self.policy.sink_assign is not None:
                for arg, desc in self.policy.sink_assign(node):
                    self._hit(node, desc, self.eval(arg, env))
            return env
        if isinstance(node, ast.AugAssign):
            self.scan(node.value, env)
            labels = self.eval(node.value, env)
            if isinstance(node.target, ast.Name):
                env.setdefault(node.target.id, set()).update(labels)
            elif isinstance(node.target, ast.Subscript):
                self.assign(node.target, labels, env)
            return env
        if isinstance(node, ast.Delete):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    env.pop(t.id, None)
            return env
        if isinstance(node, ast.Expr):
            self.scan(node.value, env)
            return env
        if isinstance(node, ast.If):
            self.scan(node.test, env)
            t_env = self.run_body(list(node.body), _copy_env(env))
            e_env = self.run_body(list(node.orelse), _copy_env(env))
            return _join_env(t_env, e_env)
        if isinstance(node, (ast.While, ast.For, ast.AsyncFor)):
            self._breaks.append([])
            loop_env: Optional[Dict[str, Set[str]]] = _copy_env(env)
            for _ in range(LOOP_PASSES):
                it_env = _copy_env(loop_env)
                assert it_env is not None
                if isinstance(node, ast.While):
                    self.scan(node.test, it_env)
                else:
                    self.scan(node.iter, it_env)
                    labels = self.eval(node.iter, it_env)
                    for t in ast.walk(node.target):
                        if isinstance(t, ast.Name):
                            it_env[t.id] = set(labels)
                self._continues.append([])
                body_out = self.run_body(list(node.body), it_env)
                for c in self._continues.pop():
                    body_out = _join_env(body_out, c)
                merged = _join_env(loop_env, body_out)
                if merged == loop_env:
                    break
                loop_env = merged
            breaks = self._breaks.pop()
            normal: Optional[Dict[str, Set[str]]] = _copy_env(loop_env)
            if node.orelse:
                normal = self.run_body(list(node.orelse), normal)
            out = normal
            for b in breaks:
                out = _join_env(out, b)
            return out
        if isinstance(node, (ast.With, ast.AsyncWith)):
            cur: Optional[Dict[str, Set[str]]] = env
            for item in node.items:
                self.scan(item.context_expr, cur)
                if item.optional_vars is not None:
                    self.assign(item.optional_vars,
                                self.eval(item.context_expr, cur), cur)
            return self.run_body(list(node.body), cur)
        if isinstance(node, ast.Try):
            body_env = self.run_body(list(node.body), _copy_env(env))
            # Any statement in the body may raise: handlers see the join
            # of the entry state and the body's exit state — a sanitizer
            # call inside the try must NOT count as having run on the
            # exception path (the _spill_copy_in except-continue shape).
            h_in = _join_env(_copy_env(env), body_env)
            outs: List[Optional[Dict[str, Set[str]]]] = []
            if node.orelse:
                outs.append(self.run_body(list(node.orelse),
                                          _copy_env(body_env)))
            else:
                outs.append(body_env)
            for handler in node.handlers:
                h_env = _copy_env(h_in)
                if h_env is not None and handler.name:
                    h_env[handler.name] = set()
                outs.append(self.run_body(list(handler.body), h_env)
                            if h_env is not None else None)
            out: Optional[Dict[str, Set[str]]] = None
            for o in outs:
                out = _join_env(out, o)
            if node.finalbody:
                # finally also runs on raising/early-leaving paths.
                fin_in = _join_env(out, h_in)
                out = self.run_body(list(node.finalbody), fin_in)
            return out
        if isinstance(node, ast.Match):
            self.scan(node.subject, env)
            out: Optional[Dict[str, Set[str]]] = None
            for case in node.cases:
                out = _join_env(out, self.run_body(list(case.body),
                                                   _copy_env(env)))
            return _join_env(out, env)
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self.scan(child, env)
        return env


class InterprocTaint:
    """Fixpoint of :class:`FuncSummary` over a project call graph.

    Round k propagates facts through call chains of length ≤ k (each round
    reads the PREVIOUS round's summaries — a Jacobi iteration — so the
    round cap IS the call-depth bound the ISSUE asks for).  Summaries only
    grow: an unresolved callee starts from the empty summary, labels union
    monotonically, and recursion/cycles therefore terminate at either the
    fixpoint or the ``max_depth`` cutoff, whichever comes first.

    Callee resolution is name-keyed like the rest of tunnelcheck: every
    same-name def must agree on signature shape, otherwise the call is
    treated as unknown and its result is conservatively as dirty as its
    arguments.  Higher-order flow (``run_in_executor(None, self._fn, x)``)
    and closure capture are invisible — the same blind spots as
    :class:`~tools.tunnelcheck.callgraph.CallGraph`, documented there.
    """

    def __init__(self, graph, policy: TaintPolicy, max_depth: int = 4):
        self.graph = graph
        self.policy = policy
        self.max_depth = max(1, max_depth)
        self.rounds = 0
        self.summaries: Dict[int, FuncSummary] = {}
        self._prev: Dict[int, FuncSummary] = {}
        self._callee_memo: Dict[
            str, Optional[Tuple[object, FuncSummary]]] = {}
        self._fixpoint()

    # -- callee lookup ----------------------------------------------------

    def _callee(self, name: str):
        if name in self._callee_memo:
            return self._callee_memo[name]
        out = None
        nodes = self.graph.by_name.get(name)
        if nodes:
            shapes = {
                (tuple(n.info.pos), n.info.has_vararg, n.info.has_kwarg,
                 n.info.is_method)
                for n in nodes
            }
            if len(shapes) == 1:
                merged = FuncSummary()
                for n in nodes:
                    s = self._prev.get(id(n.node))
                    if s is not None:
                        merged.ret |= s.ret
                        for p, d in s.sink_params.items():
                            merged.sink_params.setdefault(p, d)
                out = (nodes[0].info, merged)
        self._callee_memo[name] = out
        return out

    # -- fixpoint ---------------------------------------------------------

    def _fixpoint(self) -> None:
        all_nodes = [n for nodes in self.graph.by_name.values()
                     for n in nodes]
        pending = all_nodes
        for _ in range(self.max_depth):
            self.rounds += 1
            self._prev = self.summaries
            self.summaries = dict(self._prev)
            self._callee_memo = {}
            changed: Set[str] = set()
            for fn in pending:
                s = self.analyze(fn.node)
                old = self._prev.get(id(fn.node))
                if old is None or s.ret != old.ret \
                        or s.sink_params != old.sink_params:
                    changed.add(fn.name)
                self.summaries[id(fn.node)] = s
            if not changed:
                break
            # Only re-analyze functions whose callee set intersects what
            # changed — the worklist that keeps tree-wide runs O(edges).
            pending = [n for n in all_nodes if n.calls & changed]
            if not pending:
                break
        self._prev = self.summaries
        self._callee_memo = {}

    # -- public API -------------------------------------------------------

    def analyze(self, fn: ast.AST,
                on_sink: Optional[Callable[[ast.AST, str], None]] = None
                ) -> FuncSummary:
        """Walk one function against the current summaries.  With
        ``on_sink``, runs in reporting mode: seeds ``policy.seed_params``
        and invokes the callback at every sink reached by a label set
        containing :data:`SRC`."""
        summary = FuncSummary()
        _SummaryFlow(self, fn, summary, on_sink).run()
        return summary

    def summary_for(self, fn: ast.AST) -> Optional[FuncSummary]:
        """The fixpoint summary for a def node from the graph, if any."""
        return self.summaries.get(id(fn))


def interproc_taint(graph, policy: TaintPolicy,
                    max_depth: int = 4) -> InterprocTaint:
    """Build the interprocedural taint fixpoint for ``graph`` (a
    :class:`~tools.tunnelcheck.callgraph.CallGraph`) under ``policy``.
    ``max_depth`` bounds both the fixpoint rounds and, equivalently, the
    call-chain length facts can travel (see :class:`InterprocTaint`)."""
    return InterprocTaint(graph, policy, max_depth=max_depth)
