"""TC01: no blocking calls inside ``async def``.

transport/, endpoints/, and engine/api are asyncio-heavy; one stray
``time.sleep`` or sync socket call stalls every stream sharing the loop.
Today only ``PYTHONASYNCIODEBUG=1`` (make test-race) catches these, at
runtime, and only on paths the suites happen to exercise.  This rule makes
the invariant static: a call from the blocklist whose *nearest enclosing
function* is ``async def`` is a violation.  Nested sync defs are not
flagged — they may be destined for ``run_in_executor``.
"""

from __future__ import annotations

import ast
from typing import Iterator

from tools.tunnelcheck.core import (
    ProjectContext,
    SourceFile,
    Violation,
    collect_import_aliases,
    iter_scope_statements,
    resolve_dotted,
)

#: Canonical dotted names that block the event loop when awaited nowhere.
BLOCKING_CALLS = {
    "time.sleep": "use `await asyncio.sleep(...)`",
    "subprocess.run": "use `await asyncio.create_subprocess_exec(...)`",
    "subprocess.call": "use `await asyncio.create_subprocess_exec(...)`",
    "subprocess.check_call": "use `await asyncio.create_subprocess_exec(...)`",
    "subprocess.check_output": "use `await asyncio.create_subprocess_exec(...)`",
    "subprocess.getoutput": "use `await asyncio.create_subprocess_exec(...)`",
    "subprocess.getstatusoutput": "use `await asyncio.create_subprocess_exec(...)`",
    "os.system": "use `await asyncio.create_subprocess_shell(...)`",
    "os.popen": "use `await asyncio.create_subprocess_shell(...)`",
    "os.wait": "use `await proc.wait()`",
    "os.waitpid": "use `await proc.wait()`",
    "socket.create_connection": "use `await asyncio.open_connection(...)`",
    "socket.getaddrinfo": "use `await loop.getaddrinfo(...)`",
    "socket.gethostbyname": "use `await loop.getaddrinfo(...)`",
    "urllib.request.urlopen": "use the async http11 client",
    "requests.get": "use the async http11 client",
    "requests.post": "use the async http11 client",
    "requests.put": "use the async http11 client",
    "requests.patch": "use the async http11 client",
    "requests.delete": "use the async http11 client",
    "requests.head": "use the async http11 client",
    "requests.request": "use the async http11 client",
}

#: Builtin / method-attr calls that are blocking file IO or loop re-entry.
BLOCKING_BUILTINS = {
    "open": "blocking file IO; use `await loop.run_in_executor(...)`",
}
BLOCKING_METHOD_ATTRS = {
    "read_text": "blocking file IO (pathlib); run it in an executor",
    "read_bytes": "blocking file IO (pathlib); run it in an executor",
    "write_text": "blocking file IO (pathlib); run it in an executor",
    "write_bytes": "blocking file IO (pathlib); run it in an executor",
    "run_until_complete": "re-enters the event loop from a coroutine",
}


def check_tc01(sf: SourceFile, ctx: ProjectContext) -> Iterator[Violation]:
    del ctx
    out = []

    class Visitor(ast.NodeVisitor):
        def __init__(self) -> None:
            self.func_stack: list = []  # True for async frames, False for sync
            #: per-frame import overlays: function-local `from time import
            #: sleep` must resolve inside that function (and its nested
            #: scopes) without polluting the rest of the module.
            self.alias_stack: list = []

        def _aliases(self) -> dict:
            merged = dict(sf.aliases)
            for overlay in self.alias_stack:
                merged.update(overlay)
            return merged

        def _visit_func(self, node, is_async: bool) -> None:
            self.func_stack.append(is_async)
            self.alias_stack.append(
                collect_import_aliases(iter_scope_statements(node.body))
                if isinstance(node.body, list)  # lambdas can't import
                else {}
            )
            self.generic_visit(node)
            self.alias_stack.pop()
            self.func_stack.pop()

        def visit_AsyncFunctionDef(self, node) -> None:
            self._visit_func(node, True)

        def visit_FunctionDef(self, node) -> None:
            self._visit_func(node, False)

        def visit_Lambda(self, node) -> None:
            self._visit_func(node, False)

        def visit_Call(self, node: ast.Call) -> None:
            if self.func_stack and self.func_stack[-1]:
                self._check_call(node)
            self.generic_visit(node)

        def _check_call(self, node: ast.Call) -> None:
            resolved = resolve_dotted(node.func, self._aliases())
            if resolved in BLOCKING_CALLS:
                out.append(
                    Violation(
                        "TC01",
                        sf.path,
                        node.lineno,
                        f"blocking `{resolved}(...)` inside async def; "
                        f"{BLOCKING_CALLS[resolved]}",
                        end_line=node.end_lineno,
                    )
                )
                return
            if (
                isinstance(node.func, ast.Name)
                and node.func.id in BLOCKING_BUILTINS
                and node.func.id not in self._aliases()
            ):
                out.append(
                    Violation(
                        "TC01",
                        sf.path,
                        node.lineno,
                        f"`{node.func.id}(...)` inside async def: "
                        f"{BLOCKING_BUILTINS[node.func.id]}",
                        end_line=node.end_lineno,
                    )
                )
                return
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in BLOCKING_METHOD_ATTRS
            ):
                out.append(
                    Violation(
                        "TC01",
                        sf.path,
                        node.lineno,
                        f"`.{node.func.attr}(...)` inside async def: "
                        f"{BLOCKING_METHOD_ATTRS[node.func.attr]}",
                        end_line=node.end_lineno,
                    )
                )

    Visitor().visit(sf.tree)
    return iter(out)
