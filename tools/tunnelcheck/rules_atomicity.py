"""TC13: read-modify-write of shared mutable state across an await.

The PR 8 review incident made permanent: the peer circuit breaker's
half-open bookkeeping read ``consec_failures``, awaited the probe
dispatch, then wrote breaker state based on the *stale* read — a second
task's concurrent failure/success in the await window could wedge the
breaker half-open (or double-open it).  Nothing crashes; the fabric just
routes wrong under exactly the overlapping-failure load the breaker
exists for.  ``make test-race`` only catches what a seeded schedule
happens to interleave; this rule makes the invariant static.

Built on the shared substrate (:mod:`tools.tunnelcheck.dataflow`): each
``async def`` in the serving scope gets a CFG, and a worklist analysis
reports every write to a *shared* attribute whose guarding read — or the
local carrying the value being written — crossed an ``await``/``yield``
(both suspension points: an async generator parked at a yield has
released the loop, and ``aclose()`` may mean it never resumes).

What does NOT flag:

- re-reading after the await (the check-again idiom — the read is fresh);
- holding a lock: writes inside ``async with self._lock`` (any context
  expression with a lock-ish identifier word) are atomic sections;
- attributes only ONE function ever touches, project-wide: nothing else
  can interleave, so the single-writer contract holds by construction
  (the ``attr_function_count`` gate);
- sync defs: without an await there is no suspension to tear across
  (cross-THREAD tearing is ``make test-race``'s and the GIL's problem).

Deliberate single-task ownership (the engine ``_loop`` pattern: every
mutation of decode state happens on the one loop task) is waived per
line, naming the owning task — the waiver IS the documented contract.
"""

from __future__ import annotations

import ast
from typing import Iterator, List

from tools.tunnelcheck.core import ProjectContext, SourceFile, Violation
from tools.tunnelcheck.dataflow import (
    FuncCFG,
    attr_reach,
    iter_functions,
    param_names,
)

#: Serving-path scope: the asyncio-heavy modules whose objects are reached
#: from many tasks (request handlers, per-peer readers, probers,
#: keepalives, the engine loop).  Fixture trees reuse these path parts.
SCOPE_PARTS = (
    "p2p_llm_tunnel_tpu/endpoints/",
    "p2p_llm_tunnel_tpu/engine/",
    "p2p_llm_tunnel_tpu/transport/",
    "p2p_llm_tunnel_tpu/protocol/",
    "p2p_llm_tunnel_tpu/signaling/",
    "p2p_llm_tunnel_tpu/utils/",
)

#: An attribute is "shared" when at least this many distinct functions
#: (project-wide, any receiver) touch it — one accessor means a
#: single-writer contract by construction.
MIN_ACCESSOR_FUNCTIONS = 2


def _in_scope(sf: SourceFile) -> bool:
    p = sf.path.as_posix()
    return any(part in p for part in SCOPE_PARTS)


def check_tc13(sf: SourceFile, ctx: ProjectContext) -> Iterator[Violation]:
    if not _in_scope(sf):
        return iter(())
    out: List[Violation] = []
    for fn, _cls in iter_functions(sf.tree):
        if not isinstance(fn, ast.AsyncFunctionDef):
            continue
        # Roots that can alias pre-existing (shared) objects: self, and
        # any parameter — a caller handed it in, so another task may hold
        # it too.  Fresh locals (constructed in this activation) are not
        # tracked; publishing them is the caller's last step, after which
        # this frame no longer writes.
        roots = {"self"} | param_names(fn)

        def shared(obj: str, attr: str) -> bool:
            return ctx.attr_function_count(attr) >= MIN_ACCESSOR_FUNCTIONS

        cfg = FuncCFG(fn)
        for torn in attr_reach(cfg, roots, tracked=shared):
            where = "yield" if torn.is_yield else "await"
            via = (f" via stale local `{torn.via_local}`"
                   if torn.via_local else "")
            node = torn.node
            out.append(Violation(
                "TC13",
                sf.path,
                torn.line,
                f"read-modify-write of shared `{torn.obj}.{torn.attr}` "
                f"straddles the {where}/suspension at line "
                f"{torn.suspend_line}{via}: another task can interleave in "
                "the suspension window (the breaker half-open wedge class) "
                "— hold an asyncio.Lock across the read+write, re-read "
                "after the await, or waive naming the single-writer task "
                "that owns this state",
                end_line=getattr(node, "end_lineno", None) if node is not None
                else None,
            ))
    return iter(out)
