"""TC08: every ``EngineConfig`` field must be wired to a ``cli.py`` flag.

The config-rot counterpart to TC06 (ISSUE 5): EngineConfig grows a field
per feature, but a field nobody plumbs through the serve CLI is reachable
only by programmatic embedders and the bench's env knobs — operators of
the deployed binary simply cannot turn it on, and nothing fails.  That is
exactly how ``decode_steps_eager`` and ``prefill_rows`` sat env/bench-only
for four PRs while README documented them as serving levers.

The rule fires on every dataclass field of a class named ``EngineConfig``
that never appears as a KEYWORD in an ``EngineConfig(...)`` construction
inside a ``cli.py`` — the one place the serve subcommand assembles the
engine's config from parsed flags.  Fields that are deliberately
env/programmatic-only (e.g. bucket geometry pinned by the compiled-program
set) carry a per-line waiver naming why, so the exemption is visible and
audited (``--show-waived``) instead of folklore.

Wiring surface resolution mirrors the registry rules: a scanned ``cli.py``
wins (fixture trees test against their own), else the repo's own
``p2p_llm_tunnel_tpu/cli.py`` is parsed — so scanning ``engine/engine.py``
alone still checks against the real CLI.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Iterator, List, Optional, Set, Tuple

from tools.tunnelcheck.core import (
    REPO_ROOT,
    ProjectContext,
    SourceFile,
    Violation,
    dotted_name,
)

CONFIG_CLASS = "EngineConfig"
CLI_REL = "p2p_llm_tunnel_tpu/cli.py"


def _config_fields(
    tree: ast.Module,
) -> Optional[List[Tuple[str, int, Optional[int]]]]:
    """``[(field, line, end_line)]`` of the dataclass ``EngineConfig``
    defined in ``tree``, or None when the module defines no such class."""
    for node in ast.walk(tree):
        if not (isinstance(node, ast.ClassDef) and node.name == CONFIG_CLASS):
            continue
        fields = []
        for stmt in node.body:
            if isinstance(stmt, ast.AnnAssign) and isinstance(
                stmt.target, ast.Name
            ):
                fields.append(
                    (stmt.target.id, stmt.lineno, stmt.end_lineno)
                )
        return fields
    return None


def _wired_keywords(tree: ast.Module) -> Set[str]:
    """Keyword names of every ``EngineConfig(...)`` call in ``tree``."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        dotted = dotted_name(node.func)
        if dotted is None or dotted.split(".")[-1] != CONFIG_CLASS:
            continue
        out.update(kw.arg for kw in node.keywords if kw.arg is not None)
    return out


def _cli_keywords(ctx: ProjectContext) -> Optional[Set[str]]:
    """The wiring surface: union over scanned ``cli.py`` files, else the
    repo's own CLI module; None when neither exists (fixture-only runs
    with no CLI at all — nothing meaningful to check against)."""
    scanned = [sf for sf in ctx.files if sf.path.name == "cli.py"]
    if scanned:
        out: Set[str] = set()
        for sf in scanned:
            out |= _wired_keywords(sf.tree)
        return out
    candidate = REPO_ROOT / CLI_REL
    if candidate.is_file():
        try:
            return _wired_keywords(
                ast.parse(candidate.read_text(encoding="utf-8"))
            )
        except (OSError, SyntaxError):
            return None
    return None


def check_tc08(sf: SourceFile, ctx: ProjectContext) -> Iterator[Violation]:
    fields = _config_fields(sf.tree)
    if not fields:
        return iter(())
    wired = _cli_keywords(ctx)
    if wired is None:
        return iter(())
    out: List[Violation] = []
    for name, line, end_line in fields:
        if name in wired:
            continue
        out.append(
            Violation(
                "TC08",
                sf.path,
                line,
                f"EngineConfig.{name} is not wired to any cli.py flag "
                f"(no `{name}=` keyword in a cli.py EngineConfig(...) "
                "construction) — operators of the serve binary cannot "
                "reach it; add a flag or waive with the reason it is "
                "env/programmatic-only",
                end_line=end_line,
            )
        )
    return iter(out)
