"""TC04: optional-dependency hygiene for ``websockets`` / ``cryptography``.

PR 1 had to retroactively gate these imports after 12 tier-1 collection
errors: any module-level import of an optional dependency breaks *import*
of the whole package on machines without it — which is every CI machine
the TPU toolchain image doesn't cover.  The fix was to confine the imports
to three gated wrapper modules (try/except at import, hard error only at
first use).  This rule makes that fix permanent: a module-level import of
an optional dep anywhere else is a violation; function-local imports and
``pytest.importorskip`` remain fine.
"""

from __future__ import annotations

import ast
from typing import Iterator

from tools.tunnelcheck.core import (
    ProjectContext,
    SourceFile,
    Violation,
    iter_scope_statements,
)

#: Distributions whose absence must never break ``import p2p_llm_tunnel_tpu``.
OPTIONAL_DEPS = {"websockets", "cryptography"}

#: The gated wrappers PR 1 introduced — the only modules allowed to import
#: the optional deps at module level (inside their try/except gates).
GATED_WRAPPERS = (
    "p2p_llm_tunnel_tpu/transport/crypto.py",
    "p2p_llm_tunnel_tpu/signaling/client.py",
    "p2p_llm_tunnel_tpu/signaling/server.py",
)


def _module_level_imports(tree: ast.Module) -> Iterator[ast.stmt]:
    """Imports that execute at module import time (incl. try/if/class bodies),
    excluding anything inside a function or lambda."""
    for node in iter_scope_statements(tree.body):
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            yield node


def check_tc04(sf: SourceFile, ctx: ProjectContext) -> Iterator[Violation]:
    del ctx
    posix = sf.path.as_posix()
    if any(posix.endswith(w) for w in GATED_WRAPPERS):
        return iter(())
    out = []
    for node in _module_level_imports(sf.tree):
        roots = []
        if isinstance(node, ast.Import):
            roots = [a.name.split(".")[0] for a in node.names]
        elif isinstance(node, ast.ImportFrom) and node.module and not node.level:
            roots = [node.module.split(".")[0]]
        for root in roots:
            if root in OPTIONAL_DEPS:
                out.append(
                    Violation(
                        "TC04",
                        sf.path,
                        node.lineno,
                        f"module-level import of optional dep `{root}`; only "
                        "the gated wrappers (transport/crypto.py, signaling/"
                        "client.py, signaling/server.py) may import it — go "
                        "through them, or import inside the function that "
                        "needs it (the PR 1 collection-error incident)",
                        end_line=node.end_lineno,
                    )
                )
    return iter(out)
