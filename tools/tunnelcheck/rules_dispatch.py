"""TC07: device dispatches inside per-request/per-slot loops on the
serving path.

The r5 incident made permanent (ISSUE 4 satellite): the prefix-cache
copy-in originally dispatched ONE jitted copy per matched request inside
the admission loop — through the tunneled-TPU's ~90 ms dispatch path that
tripled prefill p50 and cut e2e throughput 1684→1053 tok/s, and nothing
failed.  The fix (batch the wave into one ``prefill_rows``-wide dispatch)
is invisible to tests on a fast local backend, so the invariant lives
here: in the engine/endpoints serving modules, a loop whose subject is
requests/slots/admissions must not contain a device dispatch per
iteration.

"Device dispatch" is resolved statically, in three layers:
- direct device ops: ``jax.device_put`` / ``jax.device_get`` /
  ``jax.block_until_ready`` and ``.block_until_ready()`` method calls;
- names bound to ``jax.jit(...)`` results — including tuple-unpacked
  results of PROJECT-WIDE factory functions whose bodies call ``jax.jit``
  (``make_batch_copy_ops``), and rebindings that pass a known name back
  through a wrapper (``self._spmd.wrap("op", self._jit_x, n)``);
- functions/methods of the same module that transitively CALL any of the
  above (the r5 class: a helper that dispatches per call, invoked from a
  request loop — directly or handed to ``run_in_executor``).

Loop subjects match word-wise (identifiers split on underscores), so
``while self._running`` — the engine's main loop, whose one dispatch per
BURST is the design — does not match, while ``for run in runs`` does.

Deliberately-batched sub-batch loops (one dispatch per prefill_rows-wide
chunk) and the pipelined admission fetch loop are the legitimate
exceptions — they carry per-line waivers with reasons, which doubles as
documentation of the dispatch-granularity contract at each site.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Set

from tools.tunnelcheck.core import (
    ProjectContext,
    SourceFile,
    Violation,
    resolve_dotted,
)

#: Serving-path modules: the engine package and the tunnel endpoints.
SCOPE_PARTS = (
    "p2p_llm_tunnel_tpu/engine/",
    "p2p_llm_tunnel_tpu/endpoints/",
)

#: Loop-subject vocabulary (matched word-wise against identifiers in the
#: loop target/iterable/condition): iteration over these means "once per
#: request-shaped thing", where a per-iteration dispatch is the r5 bug.
SUBJECT_WORDS = frozenset({
    "request", "requests", "req", "reqs",
    "slot", "slots",
    "run", "runs",
    "hit", "hits",
    "admitted", "admissions",
    "prompt", "prompts",
    "entry", "entries",
    # NOT "chunk"/"chunked": warmup iterates static chunk-width buckets
    # (engine._warm_prefix) — a compile-time loop, not a request loop; the
    # genuine chunk loops all carry runs/hits/slots identifiers too.
    "segment", "segments", "segmented",
    "dispatched",
    "wave", "waves",
    "client", "clients",
    "stream", "streams",
})

DEVICE_CALLS = {
    "jax.device_put": "jax.device_put",
    "jax.device_get": "jax.device_get",
    "jax.block_until_ready": "jax.block_until_ready",
}

_EXECUTOR_METHODS = {"run_in_executor", "submit"}


def _in_scope(sf: SourceFile) -> bool:
    p = sf.path.as_posix()
    return any(part in p for part in SCOPE_PARTS)


def _ident_words(node: ast.AST) -> Set[str]:
    words: Set[str] = set()
    for sub in ast.walk(node):
        name = None
        if isinstance(sub, ast.Name):
            name = sub.id
        elif isinstance(sub, ast.Attribute):
            name = sub.attr
        elif isinstance(sub, ast.arg):
            name = sub.arg
        if name:
            words.update(w for w in name.lower().split("_") if w)
    return words


def _callee_name(func: ast.AST) -> str:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return ""


def _project_jit_factories(ctx: ProjectContext) -> Set[str]:
    """Names of functions ANYWHERE in the scanned set whose body contains
    a ``jax.jit(...)`` call — their return values (tuples included) are
    dispatch callables, and calling them IS a trace/dispatch.  Served by
    the shared call graph (this used to be a private project-wide scan)."""
    return ctx.callgraph.functions_calling("jax.jit")


def _dispatch_names(sf: SourceFile, factories: Set[str]) -> Set[str]:
    """Variable/attribute names bound (anywhere in the file) to dispatch
    callables: jax.jit results, jit-factory results, or wrappers fed a
    known dispatch name (fixpoint for rebinding chains)."""
    names: Set[str] = set()

    def targets_of(node) -> List[str]:
        tgts = node.targets if isinstance(node, ast.Assign) else [node.target]
        out: List[str] = []
        for t in tgts:
            if isinstance(t, ast.Tuple):
                elts = t.elts
            else:
                elts = [t]
            for e in elts:
                n = _callee_name(e)
                if n:
                    out.append(n)
        return out

    changed = True
    while changed:
        changed = False
        for node in ast.walk(sf.tree):
            if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                continue
            value = getattr(node, "value", None)
            if not isinstance(value, ast.Call):
                continue
            resolved = resolve_dotted(value.func, sf.aliases)
            from_jit = resolved == "jax.jit"
            from_factory = _callee_name(value.func) in factories
            wraps_known = any(
                _callee_name(a) in names
                for a in list(value.args)
                + [kw.value for kw in value.keywords]
            )
            if from_jit or from_factory or wraps_known:
                for n in targets_of(node):
                    if n not in names:
                        names.add(n)
                        changed = True
    return names


def _dispatching_functions(
    sf: SourceFile, names: Set[str], factories: Set[str], ctx: ProjectContext
) -> Set[str]:
    """Module functions that transitively perform a device dispatch — the
    shared call graph's transitive-caller closure, seeded at defs whose
    body contains a direct device op, a jit, a dispatch-bound name, or a
    jit-factory call."""
    device_dotted = set(DEVICE_CALLS) | {"jax.jit"}

    def is_seed(fn) -> bool:
        return bool(
            fn.dotted_calls & device_dotted
            or "block_until_ready" in fn.calls
            or fn.calls & names
            or fn.calls & factories
        )

    return ctx.callgraph.transitive_callers(is_seed, within=sf.path)


def check_tc07(sf: SourceFile, ctx: ProjectContext) -> Iterator[Violation]:
    if not _in_scope(sf):
        return iter(())
    factories = _project_jit_factories(ctx)
    names = _dispatch_names(sf, factories)
    dispatching = _dispatching_functions(sf, names, factories, ctx)
    out: List[Violation] = []
    reported: Set = set()

    def report(node: ast.AST, what: str, loop: ast.AST) -> None:
        key = (node.lineno, what)
        if key in reported:
            return
        reported.add(key)
        out.append(Violation(
            "TC07",
            sf.path,
            node.lineno,
            f"device dispatch `{what}` inside a per-request/slot loop "
            f"(line {loop.lineno}) — one dispatch per iteration through "
            "the device tunnel is the r5 prefix-copy regression "
            "(1684→1053 tok/s); batch the wave into one dispatch, or "
            "waive with the dispatch-granularity contract",
            end_line=node.end_lineno,
        ))

    def subject_words(loop: ast.AST) -> Set[str]:
        if isinstance(loop, (ast.For, ast.AsyncFor)):
            return _ident_words(loop.target) | _ident_words(loop.iter)
        return _ident_words(loop.test)  # while

    def scan_loop_body(loop: ast.AST) -> None:
        bodies = loop.body + getattr(loop, "orelse", [])
        for stmt in bodies:
            for sub in ast.walk(stmt):
                if not isinstance(sub, ast.Call):
                    continue
                resolved = resolve_dotted(sub.func, sf.aliases)
                if resolved in DEVICE_CALLS:
                    report(sub, resolved, loop)
                    continue
                callee = _callee_name(sub.func)
                if callee == "block_until_ready":
                    report(sub, ".block_until_ready()", loop)
                    continue
                if callee in names or callee in dispatching \
                        or callee in factories:
                    report(sub, f"{callee}(...)", loop)
                    continue
                if callee in _EXECUTOR_METHODS:
                    # run_in_executor(executor, fn, ...) / submit(fn, ...):
                    # the handed-off callable dispatches on another thread,
                    # still once per iteration.
                    cands = sub.args[1:] if callee == "run_in_executor" \
                        else sub.args[:1]
                    for a in cands[:1]:
                        an = _callee_name(a)
                        if an in names or an in dispatching:
                            report(sub, f"{callee}({an})", loop)

    for node in ast.walk(sf.tree):
        if isinstance(node, (ast.For, ast.AsyncFor, ast.While)):
            if subject_words(node) & SUBJECT_WORDS:
                scan_loop_body(node)
    return iter(out)
