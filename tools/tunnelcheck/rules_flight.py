"""TC16: black-box field names from the flight/postmortem registries, and
ops/debug HTTP query surfaces only through ``http11.ops_route``.

Two halves of one invariant — the engine's black box (ISSUE 12) is only
trustworthy if its vocabulary and its transport are single-sourced:

1. **Schema registry** (the TC06/TC09 catalog pattern): every keyword
   handed to ``record_iteration(...)`` must be declared in
   ``utils/flight.py``'s ``FLIGHT_SCHEMA``, and any dict-literal ``slo=``
   /extra payload keys reaching ``BlackBox.capture`` must be postmortem
   schema members.  A typo'd field doesn't fail anything — it silently
   splits the black-box vocabulary between the writer and every reader
   (traceview --flight, the bundle-identity chaos test, dashboards).

2. **Ops routing**: the serve loop, proxy, and any future debug surface
   must classify ``/healthz`` / ``/metrics`` requests through
   ``http11.ops_route`` (and test query flags against its returned flag
   set), never by hand-rolled path string matching — PR 9 unified three
   hand-rolled copies that had already diverged on reordered query
   params, and ``?postmortem=1`` would have minted a fourth.  This half
   flags, inside ``endpoints/`` modules other than ``http11.py``:
   comparisons/``startswith``/membership against ``/healthz`` or
   ``/metrics`` literals, and ``"<k>=<v>" in <something>.path`` membership
   tests (query parsing that is order- and duplicate-sensitive).
"""

from __future__ import annotations

import ast
import re
from typing import Iterator, List

from tools.tunnelcheck.core import ProjectContext, SourceFile, Violation

#: The write entry point whose keyword arguments are flight-record fields.
FLIGHT_WRITE = "record_iteration"
#: The capture entry point; a literal dict bound to these keywords carries
#: postmortem top-level fields.
CAPTURE_FN = "capture"

#: Registry module (the schemas live here); its own internals are exempt
#: from the ops/record checks the way utils/metrics.py is for TC12.
REGISTRY_SUFFIX = "p2p_llm_tunnel_tpu/utils/flight.py"
#: The one module allowed to string-match ops paths.
OPS_ROUTER_SUFFIX = "p2p_llm_tunnel_tpu/endpoints/http11.py"

_OPS_PATHS = ("/healthz", "/metrics")
#: A raw query-flag token like ``trace=1`` / ``postmortem=1``.
_FLAG_RE = re.compile(r"^[A-Za-z_][A-Za-z0-9_]*=[A-Za-z0-9_]+$")

_SCHEMA_MSG = (
    "field {names} not declared in utils.flight.{registry} — black-box "
    "field names are a registry contract (the TC06 pattern): a typo here "
    "silently splits the vocabulary between the writer and every bundle/"
    "flight reader; declare the field or fix the spelling"
)
_OPS_MSG = (
    "hand-rolled ops-path matching on {literal!r} — route /healthz and "
    "/metrics requests through http11.ops_route (and test query flags "
    "against its returned flag set): per-site string matching diverges on "
    "reordered or repeated query parameters (the pre-ISSUE-9 three-copy "
    "drift class)"
)


def _is_ops_literal(node: ast.AST) -> bool:
    return (isinstance(node, ast.Constant) and isinstance(node.value, str)
            and node.value.split("?")[0] in _OPS_PATHS)


def _path_attr(node: ast.AST) -> bool:
    """Is this expression a ``<recv>.path`` attribute read (raw request
    path — the thing query flags must not be string-matched against)?"""
    return isinstance(node, ast.Attribute) and node.attr == "path"


def check_tc16(sf: SourceFile, ctx: ProjectContext) -> Iterator[Violation]:
    out: List[Violation] = []
    posix = sf.path.as_posix()
    in_registry = posix.endswith(REGISTRY_SUFFIX)

    # -- half 1: schema-registry field names ------------------------------
    flight_fields = ctx.flight_fields
    postmortem_fields = ctx.postmortem_fields
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        name = fn.attr if isinstance(fn, ast.Attribute) else (
            fn.id if isinstance(fn, ast.Name) else None
        )
        if name == FLIGHT_WRITE and flight_fields:
            bad = sorted(
                kw.arg for kw in node.keywords
                if kw.arg is not None and kw.arg not in flight_fields
            )
            if bad:
                out.append(Violation(
                    "TC16", sf.path, node.lineno,
                    _SCHEMA_MSG.format(names=bad, registry="FLIGHT_SCHEMA"),
                    end_line=node.end_lineno,
                ))
        if name == CAPTURE_FN and postmortem_fields:
            # A dict literal handed to capture(extra=...) merges into the
            # bundle top level: its keys are postmortem fields.  (The
            # ``slo=`` payload is an objective map, not schema fields.)
            for kw in node.keywords:
                if kw.arg == "extra" and isinstance(kw.value, ast.Dict):
                    bad = sorted(
                        k.value for k in kw.value.keys
                        if isinstance(k, ast.Constant)
                        and isinstance(k.value, str)
                        and k.value not in postmortem_fields
                    )
                    if bad:
                        out.append(Violation(
                            "TC16", sf.path, node.lineno,
                            _SCHEMA_MSG.format(
                                names=bad, registry="POSTMORTEM_SCHEMA"
                            ),
                            end_line=node.end_lineno,
                        ))

    # -- half 2: ops routing only via http11.ops_route --------------------
    if ("/endpoints/" not in posix or posix.endswith(OPS_ROUTER_SUFFIX)
            or in_registry):
        return iter(out)
    for node in ast.walk(sf.tree):
        literal = None
        if isinstance(node, ast.Compare):
            # `req.path == "/healthz"` / `"/healthz" in path` — but flag
            # membership of raw query tokens ONLY against a `.path`
            # expression: `"trace=1" in route[1]` (ops_route's flag set)
            # is the sanctioned pattern.
            sides = [node.left] + list(node.comparators)
            for side in sides:
                if _is_ops_literal(side):
                    literal = side.value
            if literal is None and isinstance(
                node.ops[0], (ast.In, ast.NotIn)
            ):
                lhs = node.left
                if (isinstance(lhs, ast.Constant)
                        and isinstance(lhs.value, str)
                        and _FLAG_RE.match(lhs.value)
                        and any(_path_attr(c) for c in node.comparators)):
                    literal = lhs.value
        elif (isinstance(node, ast.Call)
              and isinstance(node.func, ast.Attribute)
              and node.func.attr == "startswith"
              and node.args and _is_ops_literal(node.args[0])):
            literal = node.args[0].value
        if literal is not None:
            out.append(Violation(
                "TC16", sf.path, node.lineno,
                _OPS_MSG.format(literal=literal),
                end_line=node.end_lineno,
            ))
    return iter(out)
