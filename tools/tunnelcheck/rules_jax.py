"""TC02 + TC03: jit-boundary signature drift and host syncs inside traces.

TC02 is the PR 2 incident made permanent: ``scripts/perf_probe.py`` carried
``jax.jit(eng._decode_fn, static_argnums=(10, 11)).lower(<12 args>)`` after
``_decode_fn`` grew a ``bias`` parameter (13 args) — broken for every quant
mode, unnoticed because tests never import scripts/.  The rule cross-checks
``static_argnums``/``static_argnames``/``donate_argnums``/``donate_argnames``
against the wrapped function's statically-resolved signature, and checks the
arity of an immediately-invoked (or ``.lower()``-ed) jitted callable.

TC03 flags host synchronisation inside functions that this module jits or
feeds to ``lax.scan``: ``.item()``, ``np.asarray``/``np.array``,
``jax.device_get``, ``float()``/``int()``/``bool()`` on jax expressions, and
Python ``if`` over a traced comparison — each is either a tracer error at
best or a silent every-step device sync at worst.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Tuple

from tools.tunnelcheck.core import (
    FuncInfo,
    ProjectContext,
    SourceFile,
    Violation,
    resolve_dotted,
)

JIT_NAMES = {"jax.jit"}
#: lax control-flow entries -> which positional args are traced functions
#: (scan(f, init, xs); while_loop(cond, body, init); fori_loop(lo, hi, body, init)).
TRACE_ENTRY_FN_ARGS = {
    "jax.lax.scan": (0,),
    "jax.lax.while_loop": (0, 1),
    "jax.lax.fori_loop": (2,),
}
PARTIAL_NAMES = {"functools.partial"}
ARGNUM_KWARGS = ("static_argnums", "donate_argnums")
ARGNAME_KWARGS = ("static_argnames", "donate_argnames")


def _is_jit_call(node: ast.AST, sf: SourceFile) -> bool:
    return (
        isinstance(node, ast.Call)
        and resolve_dotted(node.func, sf.aliases) in JIT_NAMES
    )


def _jit_target(call: ast.Call) -> Optional[ast.AST]:
    """The wrapped function of a jit call — positional or ``fun=``."""
    if call.args:
        return call.args[0]
    for kw in call.keywords:
        if kw.arg == "fun":
            return kw.value
    return None


def _literal_ints(node: ast.AST) -> Optional[List[int]]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            if not (isinstance(e, ast.Constant) and isinstance(e.value, int)):
                return None
            out.append(e.value)
        return out
    return None


def _literal_strs(node: ast.AST) -> Optional[List[str]]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            if not (isinstance(e, ast.Constant) and isinstance(e.value, str)):
                return None
            out.append(e.value)
        return out
    return None


def _resolve_target(
    target: ast.AST, sf: SourceFile, ctx: ProjectContext
) -> Tuple[Optional[FuncInfo], bool]:
    """(signature, drop_self) for a jitted expression, or (None, False).

    Resolution goes through the SHARED call graph (same-file defs win,
    project-wide defs must agree on shape) — the cross-file half TC02
    originally carried privately, now substrate.  ``obj.meth`` drops
    ``self`` (attribute access binds it); a bare name that resolves to a
    method project-wide is skipped as ambiguous.
    """
    if isinstance(target, ast.Lambda):
        return FuncInfo.from_node(target, sf.path), False
    if isinstance(target, ast.Name):
        info = ctx.callgraph.resolve(target.id, prefer_path=sf.path)
        if info is not None and info.is_method:
            return None, False
        return info, False
    if isinstance(target, ast.Attribute):
        info = ctx.callgraph.resolve(target.attr, prefer_path=sf.path)
        if info is None:
            return None, False
        return info, info.is_method
    return None, False


def _check_static_kwargs(
    keywords: List[ast.keyword],
    info: FuncInfo,
    drop_self: bool,
    lineno: int,
    sf: SourceFile,
) -> Iterator[Violation]:
    pos = info.effective_pos(drop_self)
    for kw in keywords:
        if kw.arg in ARGNUM_KWARGS:
            idxs = _literal_ints(kw.value)
            if idxs is None:
                continue
            for i in idxs:
                if info.has_vararg:
                    continue
                if i >= len(pos) or i < -len(pos):
                    yield Violation(
                        "TC02",
                        sf.path,
                        lineno,
                        f"{kw.arg} index {i} is out of range for "
                        f"`{info.name}` ({len(pos)} positional parameters: "
                        f"{', '.join(pos) or 'none'})",
                        end_line=kw.value.end_lineno,
                    )
        elif kw.arg in ARGNAME_KWARGS:
            names = _literal_strs(kw.value)
            if names is None or info.has_kwarg:
                continue
            valid = set(pos) | set(info.kwonly)
            for n in names:
                if n not in valid:
                    yield Violation(
                        "TC02",
                        sf.path,
                        lineno,
                        f"{kw.arg} names `{n}`, which is not a parameter of "
                        f"`{info.name}` (has: {', '.join(pos + info.kwonly)})",
                        end_line=kw.value.end_lineno,
                    )


def _check_call_binding(
    outer: ast.Call,
    info: FuncInfo,
    drop_self: bool,
    label: str,
    sf: SourceFile,
) -> Iterator[Violation]:
    if any(isinstance(a, ast.Starred) for a in outer.args):
        return
    if any(kw.arg is None for kw in outer.keywords):
        return
    pos = info.effective_pos(drop_self)
    n_given = len(outer.args)
    if n_given > len(pos) and not info.has_vararg:
        yield Violation(
            "TC02",
            sf.path,
            outer.lineno,
            f"{label} `{info.name}` passes {n_given} positional args but the "
            f"wrapped function takes only {len(pos)}",
            end_line=outer.end_lineno,
        )
        return
    bound = set(pos[: min(n_given, len(pos))])
    for kw in outer.keywords:
        if kw.arg in pos or kw.arg in info.kwonly:
            bound.add(kw.arg)
        elif not info.has_kwarg:
            yield Violation(
                "TC02",
                sf.path,
                outer.lineno,
                f"{label} `{info.name}` passes unknown keyword `{kw.arg}`",
                end_line=outer.end_lineno,
            )
    required = pos[: len(pos) - info.n_pos_defaults] if info.n_pos_defaults else pos
    missing = [p for p in required if p not in bound]
    missing += [k for k in info.kwonly_required if k not in bound]
    if missing:
        yield Violation(
            "TC02",
            sf.path,
            outer.lineno,
            f"{label} `{info.name}` binds {len(bound)} of "
            f"{len(required) + len(info.kwonly_required)} required parameters "
            f"— missing: {', '.join(missing)} (the PR 2 perf_probe bug class)",
            end_line=outer.end_lineno,
        )


def check_tc02(sf: SourceFile, ctx: ProjectContext) -> Iterator[Violation]:
    out: List[Violation] = []

    for node in ast.walk(sf.tree):
        # jax.jit(target, static_argnums=..., ...) expression sites.
        if _is_jit_call(node, sf):
            target = _jit_target(node)
            info, drop_self = (
                _resolve_target(target, sf, ctx) if target is not None
                else (None, False)
            )
            if info is not None:
                out.extend(
                    _check_static_kwargs(
                        node.keywords, info, drop_self, node.lineno, sf
                    )
                )
        # Immediate invocation / .lower() of a jit expression: arity check.
        if isinstance(node, ast.Call):
            inner: Optional[ast.Call] = None
            label = "call to jitted"
            if _is_jit_call(node.func, sf):
                inner = node.func  # jax.jit(f, ...)(args)
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "lower"
                and _is_jit_call(node.func.value, sf)
            ):
                inner = node.func.value  # jax.jit(f, ...).lower(args)
                label = "`.lower()` of jitted"
            if inner is not None:
                target = _jit_target(inner)
                if target is not None:
                    info, drop_self = _resolve_target(target, sf, ctx)
                    if info is not None:
                        out.extend(
                            _check_call_binding(node, info, drop_self, label, sf)
                        )
        # Decorator sites: @jax.jit(...) / @functools.partial(jax.jit, ...).
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for deco in node.decorator_list:
                if not isinstance(deco, ast.Call):
                    continue
                resolved = resolve_dotted(deco.func, sf.aliases)
                keywords = None
                if resolved in JIT_NAMES and not deco.args:
                    keywords = deco.keywords
                elif (
                    resolved in PARTIAL_NAMES
                    and deco.args
                    and resolve_dotted(deco.args[0], sf.aliases) in JIT_NAMES
                ):
                    keywords = deco.keywords
                if keywords:
                    info = FuncInfo.from_node(node, sf.path)
                    out.extend(
                        _check_static_kwargs(
                            keywords, info, False, deco.lineno, sf
                        )
                    )
    return iter(out)


# ---------------------------------------------------------------------------
# TC03
# ---------------------------------------------------------------------------

HOST_SYNC_CALLS = {
    "jax.device_get": "copies the array to host, blocking the trace",
    "numpy.asarray": "materialises the traced array on host",
    "numpy.array": "materialises the traced array on host",
}


def _module_defs(sf: SourceFile, ctx: ProjectContext) -> Dict[str, List[ast.AST]]:
    """name -> defs in this module, served from the shared call graph's
    per-file index instead of a private ``ast.walk`` copy."""
    defs: Dict[str, List[ast.AST]] = {}
    for fn in ctx.callgraph.by_path.get(sf.path, []):
        defs.setdefault(fn.name, []).append(fn.node)
    return defs


def _fn_param_names(fn: ast.AST) -> List[str]:
    a = fn.args
    out = [x.arg for x in a.posonlyargs + a.args]
    if a.vararg:
        out.append(a.vararg.arg)
    out += [x.arg for x in a.kwonlyargs]
    if a.kwarg:
        out.append(a.kwarg.arg)
    return out


def _static_param_names(
    fn: ast.AST, drop_self: bool, keywords: "Optional[List[ast.keyword]]"
) -> "set[str]":
    """Params marked static at the jit site — Python values under trace,
    so concretising/branching on them is legal."""
    pos = [x.arg for x in fn.args.posonlyargs + fn.args.args]
    if drop_self and pos and pos[0] in ("self", "cls"):
        pos = pos[1:]
    out: set = set()
    for kw in keywords or []:
        if kw.arg == "static_argnums":
            for i in _literal_ints(kw.value) or []:
                if -len(pos) <= i < len(pos):
                    out.add(pos[i])
        elif kw.arg == "static_argnames":
            out.update(_literal_strs(kw.value) or [])
    return out


#: Array properties that are static (plain Python values) under trace:
#: branching or concretising on these is legal and common.
STATIC_ACCESSOR_ATTRS = {"shape", "ndim", "dtype", "size", "itemsize"}
STATIC_ACCESSOR_CALLS = {
    "jax.numpy.ndim",
    "jax.numpy.shape",
    "jax.numpy.size",
    "jax.numpy.result_type",
    "jax.eval_shape",
}


def _is_static_accessor(sub: ast.AST, sf: SourceFile) -> bool:
    if isinstance(sub, ast.Attribute) and sub.attr in STATIC_ACCESSOR_ATTRS:
        return True
    if isinstance(sub, ast.Subscript):  # x.shape[0]
        return _is_static_accessor(sub.value, sf)
    return (
        isinstance(sub, ast.Call)
        and resolve_dotted(sub.func, sf.aliases) in STATIC_ACCESSOR_CALLS
    )


def _static_subtree_ids(node: ast.AST, sf: SourceFile) -> set:
    """ids of every AST node under a static accessor (x.shape, jnp.ndim(x)).

    A comparison with a static accessor on either side is static as a whole
    (``x.dtype == jnp.int8`` compares two plain Python values), so the full
    Compare subtree is exempted in that case.
    """
    exempt: set = set()
    for sub in ast.walk(node):
        if _is_static_accessor(sub, sf):
            exempt.update(id(n) for n in ast.walk(sub))
        elif isinstance(sub, ast.Compare) and any(
            _is_static_accessor(s, sf) for s in [sub.left] + sub.comparators
        ):
            exempt.update(id(n) for n in ast.walk(sub))
    return exempt


def _traced_functions(
    sf: SourceFile, ctx: ProjectContext
) -> List[Tuple[ast.AST, "set[str]"]]:
    """(node, static_param_names) for every function/lambda this module jits
    or hands to lax control flow."""
    defs = _module_defs(sf, ctx)
    traced: Dict[int, list] = {}  # id(node) -> [node, static names]

    def mark(node: ast.AST, statics: "set[str]") -> None:
        entry = traced.setdefault(id(node), [node, set(statics)])
        # Jitted at several sites: only params static at EVERY site are
        # safely static.
        entry[1] &= statics

    def mark_target(target: ast.AST, keywords=None) -> None:
        # Same-name defs in sibling scopes (factory functions) are all
        # marked: a name jitted anywhere in the module is traced in every
        # incarnation for our purposes.
        if isinstance(target, ast.Lambda):
            mark(target, _static_param_names(target, False, keywords))
        elif isinstance(target, ast.Name):
            for d in defs.get(target.id, []):
                mark(d, _static_param_names(d, False, keywords))
        elif isinstance(target, ast.Attribute):
            for d in defs.get(target.attr, []):
                mark(d, _static_param_names(d, True, keywords))

    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Call):
            resolved = resolve_dotted(node.func, sf.aliases)
            if resolved in JIT_NAMES:
                target = _jit_target(node)
                if target is not None:
                    mark_target(target, node.keywords)
            elif resolved in TRACE_ENTRY_FN_ARGS:
                # Only the function positions are traced — the carry/init
                # args may share a name with a host-side def and must not
                # drag it into the traced set.
                for i in TRACE_ENTRY_FN_ARGS[resolved]:
                    if i < len(node.args):
                        mark_target(node.args[i])
            elif (
                resolved in PARTIAL_NAMES
                and node.args
                and resolve_dotted(node.args[0], sf.aliases) in JIT_NAMES
                and len(node.args) > 1
            ):
                mark_target(node.args[1], node.keywords)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for deco in node.decorator_list:
                resolved = resolve_dotted(deco, sf.aliases)
                if resolved in JIT_NAMES:
                    mark(node, set())
                elif isinstance(deco, ast.Call):
                    dres = resolve_dotted(deco.func, sf.aliases)
                    if dres in JIT_NAMES or (
                        dres in PARTIAL_NAMES
                        and deco.args
                        and resolve_dotted(deco.args[0], sf.aliases) in JIT_NAMES
                    ):
                        mark(node, _static_param_names(node, False, deco.keywords))
    return [(entry[0], entry[1]) for entry in traced.values()]


def check_tc03(sf: SourceFile, ctx: ProjectContext) -> Iterator[Violation]:
    reported: set = set()
    out: List[Violation] = []

    def report(line: int, msg: str, end_line=None) -> None:
        if (line, msg) not in reported:
            reported.add((line, msg))
            out.append(Violation("TC03", sf.path, line, msg, end_line=end_line))

    for fn, statics in _traced_functions(sf, ctx):
        fn_name = getattr(fn, "name", "<lambda>")
        traced_params = set(_fn_param_names(fn)) - statics

        def _traced_mention(expr: ast.AST) -> bool:
            """A jax value in a non-static position: either a jax-aliased
            name or a traced parameter of this function."""
            exempt = _static_subtree_ids(expr, sf)
            for sub in ast.walk(expr):
                if isinstance(sub, ast.Name) and id(sub) not in exempt:
                    if sub.id in traced_params:
                        return True
                    origin = sf.aliases.get(
                        sub.id, sub.id if sub.id == "jax" else ""
                    )
                    if origin.split(".")[0] == "jax":
                        return True
            return False

        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                if (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr == "item"
                    and not node.args
                ):
                    report(
                        node.lineno,
                        f"`.item()` inside traced `{fn_name}` forces a host "
                        "round-trip every step",
                        node.end_lineno,
                    )
                    continue
                resolved = resolve_dotted(node.func, sf.aliases)
                if resolved in HOST_SYNC_CALLS:
                    report(
                        node.lineno,
                        f"`{resolved}` inside traced `{fn_name}` "
                        f"{HOST_SYNC_CALLS[resolved]}",
                        node.end_lineno,
                    )
                    continue
                if (
                    isinstance(node.func, ast.Name)
                    and node.func.id in ("float", "int", "bool")
                    and node.func.id not in sf.aliases
                    and len(node.args) == 1
                    and _traced_mention(node.args[0])
                ):
                    report(
                        node.lineno,
                        f"`{node.func.id}(...)` on a traced value inside "
                        f"`{fn_name}` is a concretisation error under "
                        "jit (or a silent sync outside it)",
                        node.end_lineno,
                    )
            elif isinstance(node, ast.If):
                # `is`/`is not` never concretise (tracer identity is a
                # host-side check, e.g. `if mask is not None`), so only
                # value comparisons count.
                for cmp_node in ast.walk(node.test):
                    if (
                        isinstance(cmp_node, ast.Compare)
                        and any(
                            not isinstance(op, (ast.Is, ast.IsNot))
                            for op in cmp_node.ops
                        )
                        and _traced_mention(cmp_node)
                    ):
                        report(
                            node.lineno,
                            f"Python `if` over a traced comparison inside "
                            f"`{fn_name}`; use `jnp.where`/`lax.cond`",
                            node.test.end_lineno,
                        )
                        break
    return iter(out)
