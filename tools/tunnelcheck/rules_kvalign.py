"""TC19: packed-KV bytes only land in a cache plane through the
byte-aligned write helpers.

The ISSUE 17 incident class this rule makes permanent: the packed int4 KV
plane stores two tokens per byte, so any write at an odd token position
(or of an odd token count) shares its edge bytes with neighbouring tokens
that are NOT part of the write.  A plain ``plane.at[...].set(pack_int4(v))``
at such a position clobbers the neighbour's nibble — and for two rounds
the engine's answer was a *fence*: ``spec_ngram`` (whose verify bursts
start at arbitrary parity) was disabled outright whenever
``kv_quant=int4``.  ISSUE 17 deleted that fence by concentrating every
packed write into four audited helpers in :mod:`p2p_llm_tunnel_tpu.models.
quant` — ``write_packed_prefix`` / ``write_packed_chunk`` /
``append_packed_token`` / ``splice_packed_rows`` — each of which gathers
the covering whole bytes, merges boundary nibbles in registers, and
scatters whole bytes back.  This rule is the static guard that keeps the
fence dead: a new call site that packs nibbles by hand and writes them
into a plane is exactly how the clobber (and then the fence) comes back.

Two findings, both on the :func:`taint_locals` substrate (TC14's
flow-insensitive lattice — for an integrity rule, over-approximation is
the right failure direction):

- **packed-taint**: the result of a ``pack_int4(...)`` call (or a local it
  flowed into) reaches a buffer-write sink — ``.at[...].set`` /
  ``.at[...].add``, ``jax.lax.dynamic_update_slice`` /
  ``dynamic_update_index_in_dim`` / ``dynamic_update_slice_in_dim``.
- **hand-rolled nibble merge**: a buffer-write sink whose value expression
  does its own nibble surgery (a shift-by-4 combined with a bitwise OR) —
  the pre-helper RMW idiom, which evades the taint finding by never
  calling ``pack_int4``.

The four helper bodies themselves are the sanctioned commit points
(``BYTE_ALIGNED_HELPERS``) and are skipped; everything else routes through
them, registers a new audited helper here, or waives naming why the write
cannot touch a packed plane.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Set

from tools.tunnelcheck.core import ProjectContext, SourceFile, Violation
from tools.tunnelcheck.dataflow import (
    call_name,
    expr_tainted,
    iter_functions,
    taint_locals,
)

SCOPE_PART = "p2p_llm_tunnel_tpu/"

#: The audited byte-aligned commit points (models/quant.py): the ONLY
#: function bodies where a pack_int4 result may meet a buffer write.
BYTE_ALIGNED_HELPERS = frozenset({
    "write_packed_prefix",
    "write_packed_chunk",
    "append_packed_token",
    "splice_packed_rows",
})

#: The packer whose result is "packed bytes" — the taint source.
PACKERS = frozenset({"pack_int4"})

#: Functional buffer-write entry points beyond ``.at[...].set``.
UPDATE_CALLS = frozenset({
    "dynamic_update_slice",
    "dynamic_update_index_in_dim",
    "dynamic_update_slice_in_dim",
})


def _in_scope(sf: SourceFile) -> bool:
    return SCOPE_PART in sf.path.as_posix()


def _is_packed_source(expr: ast.AST) -> bool:
    return isinstance(expr, ast.Call) and call_name(expr) in PACKERS


def _at_buffer_write(node: ast.Call) -> bool:
    """``arr.at[...].set(x)`` / ``.add(x)`` — the functional buffer write."""
    return (
        isinstance(node.func, ast.Attribute)
        and node.func.attr in ("set", "add")
        and isinstance(node.func.value, ast.Subscript)
        and isinstance(node.func.value.value, ast.Attribute)
        and node.func.value.value.attr == "at"
    )


def _nibble_merge(expr: ast.AST) -> bool:
    """Hand-rolled pack: a shift-by-4 AND a bitwise OR in one value
    expression — the ``(hi << 4) | lo`` RMW idiom the helpers replaced."""
    shift = or_ = False
    for sub in ast.walk(expr):
        if isinstance(sub, ast.BinOp):
            if isinstance(sub.op, (ast.LShift, ast.RShift)) and (
                isinstance(sub.right, ast.Constant) and sub.right.value == 4
            ):
                shift = True
            elif isinstance(sub.op, ast.BitOr):
                or_ = True
        elif isinstance(sub, ast.Call) and call_name(sub) in (
            "left_shift", "right_shift"
        ):
            args = sub.args
            if len(args) == 2 and isinstance(args[1], ast.Constant) \
                    and args[1].value == 4:
                shift = True
        elif isinstance(sub, ast.Call) and call_name(sub) in (
            "bitwise_or", "bitwise_or_"
        ):
            or_ = True
    return shift and or_


def check_tc19(sf: SourceFile, ctx: ProjectContext) -> Iterator[Violation]:
    del ctx
    if not _in_scope(sf):
        return iter(())
    out: List[Violation] = []
    reported: Set[int] = set()

    def report(node: ast.AST, what: str) -> None:
        if node.lineno in reported:
            return
        reported.add(node.lineno)
        out.append(Violation(
            "TC19",
            sf.path,
            node.lineno,
            f"packed-KV bytes reach a cache-plane write outside the "
            f"byte-aligned helpers ({what}) — odd-parity edge bytes "
            "shared with neighbouring tokens get clobbered, which is the "
            "bug the spec_ngram x kv_quant=int4 fence existed to hide "
            "(ISSUE 17 deleted it): route the write through "
            "write_packed_prefix / write_packed_chunk / "
            "append_packed_token / splice_packed_rows (models/quant.py), "
            "register a new audited helper in "
            "rules_kvalign.BYTE_ALIGNED_HELPERS, or waive naming why the "
            "target is not a packed plane",
            end_line=getattr(node, "end_lineno", None),
        ))

    for fn, _cls in iter_functions(sf.tree):
        if fn.name in BYTE_ALIGNED_HELPERS:
            continue  # the sanctioned commit points
        tainted = taint_locals(fn, _is_packed_source, frozenset())
        for sub in ast.walk(fn):
            if not isinstance(sub, ast.Call):
                continue
            if isinstance(sub.func, (ast.Name, ast.Attribute)) and (
                call_name(sub) in UPDATE_CALLS
            ):
                vals = list(sub.args) + [kw.value for kw in sub.keywords]
            elif _at_buffer_write(sub):
                vals = list(sub.args) + [kw.value for kw in sub.keywords]
            else:
                continue
            if any(
                expr_tainted(a, tainted, _is_packed_source, frozenset())
                for a in vals
            ):
                report(sub, "a pack_int4 result flows into the write")
            elif _at_buffer_write(sub) and any(
                _nibble_merge(a) for a in vals
            ):
                report(sub, "hand-rolled nibble merge in the written value")
    return iter(out)
