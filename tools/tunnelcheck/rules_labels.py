"""TC12: labeled Prometheus series only through the bounded registry.

A hand-rolled ``f'{name}{{tenant="{t}"}} {v}'`` exposition line bypasses
every bound the registry enforces: the TENANT_CAP / LABELED_CAP eviction
that keeps adversarial label minting from exploding series cardinality
(ISSUE 7's x-api-key minter, ISSUE 9's peer/objective labels), and the
label-value escaping that keeps a quote inside a tenant name from
corrupting the whole exposition.  One interpolation site that drifts from
the registry's rendering also silently splits the series it duplicates —
the TC06 class, label edition.

``utils/metrics.py`` is the ONE module allowed to interpolate label
syntax (``prom_sample`` / ``prom_label_escape`` / ``prometheus_text`` /
the federation merger live there); everywhere else must WRITE through the
bounded helpers (``Metrics.set_labeled_gauge``, the ``tenant_*`` methods)
and render through the registry.  This rule flags label-pattern literals
(``{key="``) in any INTERPOLATING string construction — f-strings,
``%``-formatting, ``str.format`` — outside that module.  Plain string
constants (test assertions against exposition output, docstrings) carry
no cardinality risk and are ignored.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator, List

from tools.tunnelcheck.core import ProjectContext, SourceFile, Violation

#: A Prometheus label assignment inside a literal: ``{key="`` (f-string
#: sources double the braces, but the AST constant carries one).
LABEL_RE = re.compile(r"\{\s*[A-Za-z_][A-Za-z0-9_]*\s*=\s*\"")

#: The registry module — the one place label interpolation is legal.
REGISTRY_SUFFIX = "p2p_llm_tunnel_tpu/utils/metrics.py"

_MSG = (
    "labeled Prometheus series interpolated by hand — produce it through "
    "the bounded registry helpers (Metrics.set_labeled_gauge / the "
    "tenant_* methods, rendered by prometheus_text/prom_sample in "
    "utils/metrics.py) instead: raw label interpolation bypasses the "
    "cardinality caps and label escaping (the exposition-explosion class)"
)


def _fstring_has_label_literal(node: ast.JoinedStr) -> bool:
    has_pattern = any(
        isinstance(v, ast.Constant) and isinstance(v.value, str)
        and LABEL_RE.search(v.value)
        for v in node.values
    )
    has_interp = any(
        isinstance(v, ast.FormattedValue) for v in node.values
    )
    return has_pattern and has_interp


def check_tc12(sf: SourceFile, ctx: ProjectContext) -> Iterator[Violation]:
    if sf.path.as_posix().endswith(REGISTRY_SUFFIX):
        return iter(())
    out: List[Violation] = []
    for node in ast.walk(sf.tree):
        hit = False
        if isinstance(node, ast.JoinedStr):
            hit = _fstring_has_label_literal(node)
        elif (
            isinstance(node, ast.BinOp)
            and isinstance(node.op, ast.Mod)
            and isinstance(node.left, ast.Constant)
            and isinstance(node.left.value, str)
            and LABEL_RE.search(node.left.value)
        ):
            hit = True
        elif (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "format"
            and isinstance(node.func.value, ast.Constant)
            and isinstance(node.func.value.value, str)
            and LABEL_RE.search(node.func.value.value)
        ):
            hit = True
        if hit:
            out.append(
                Violation(
                    "TC12",
                    sf.path,
                    node.lineno,
                    _MSG,
                    end_line=node.end_lineno,
                )
            )
    return iter(out)
