"""TC15: spans, slots, and in-flight registrations must be released on
every CFG exit path — including generator ``aclose()`` at a yield.

The PR 6 incident made permanent: the engine's ``generate()`` recorded a
request's finish state AFTER its final ``yield``.  A consumer that stops
iterating once it has the last event closes the generator AT the yield
(GeneratorExit), the post-yield code never runs, and the trace journal
logged every normal finish as "cancelled" — the span effectively leaked
its true lifecycle.  The shipped fix moved the bookkeeping before the
yield and the span emission into ``finally``; this rule makes that shape
mandatory for every paired acquire/release the serving path owns.

Three registered acquire kinds (the lifecycle registry):

- ``X.open(...)`` paired with ``X.close(...)`` — the FlowControl
  per-stream window is the canonical instance;
- in-flight registrations: ``R[key] = value`` where ``R``'s name carries
  an in-flight word (``pending``, ``requests``, ``inflight``) paired with
  ``R.pop(...)`` / ``del R[key]`` / ``R.clear()`` — the PeerSet demux
  queues and the engine's active-request map;
- span identities: ``sid = new_span_id()`` paired with an
  ``add_span(..., span_id=sid)`` emission — an allocated identity that
  never reaches ``add_span`` is a hole in the trace exactly where the
  request died.

An acquire is satisfied when a matching release sits in a ``finally``
(it then runs on every exit path of its try, including GeneratorExit
raised at an interior yield), or when acquire and release are
straight-line in the same block with no suspension (``await``/``yield``),
``return``, or ``raise`` between them.  Anything else — and in particular
a release placed after a loop that yields — is exactly the incident:
the consumer may never come back.

The finally-satisfaction is deliberately function-scoped and
position-blind (a matching release in ANY ``finally`` of the function
counts): the leak window between an acquire and a directly following
``try`` is visible to review, while the miss this rule exists for — no
finally at all on a suspending path — is what actually shipped.
Cross-function lifecycles (acquire here, release in a sibling method)
cannot be proven by a per-function CFG and must carry a waiver naming
the releasing owner — the waiver is the documented contract.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from tools.tunnelcheck.core import ProjectContext, SourceFile, Violation
from tools.tunnelcheck.dataflow import call_name, iter_functions, param_names

SCOPE_PARTS = (
    "p2p_llm_tunnel_tpu/endpoints/",
    "p2p_llm_tunnel_tpu/engine/",
    "p2p_llm_tunnel_tpu/transport/",
    "p2p_llm_tunnel_tpu/protocol/",
    "p2p_llm_tunnel_tpu/signaling/",
)

#: Word-wise match on the registry attribute's name: ``link.pending``,
#: ``self._requests``, ``self._inflight_prefix``, ``self._detached`` (the
#: ISSUE 13 detached-stream registry: a registration that never releases
#: IS a replay-journal leak — bytes retained forever for a stream nobody
#: can resume).  A BARE name only counts when it is a function parameter —
#: a passed-in shared registry; a local ``pending_lp`` accumulation buffer
#: dies with the frame and needs no release.
INFLIGHT_WORDS = frozenset({"pending", "inflight", "requests", "detached"})

#: ``X.open()``/``X.close()`` pairing applies when the receiver's name
#: carries a resource word (the FlowControl per-stream window, channels,
#: connections) — NOT to every ``.open()`` spelling: a crypto
#: ``box.open(ciphertext)`` is a decrypt, not an acquire.
OPEN_WORDS = frozenset({
    "flow", "window", "channel", "conn", "connection", "stream", "slot",
    "slots", "file",
})

SPAN_ALLOC = "new_span_id"
SPAN_EMIT = "add_span"


def _in_scope(sf: SourceFile) -> bool:
    p = sf.path.as_posix()
    return any(part in p for part in SCOPE_PARTS)


def _chain(node: ast.AST) -> Optional[str]:
    """Dotted receiver chain ("link.pending", "self._requests"), or None
    when anything but plain Name/Attribute hops appear."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _words(chain: str) -> Set[str]:
    return set(chain.rsplit(".", 1)[-1].lower().split("_"))


def _inflight_chain(node: ast.AST, params: Set[str]) -> Optional[str]:
    chain = _chain(node)
    if chain is None:
        return None
    if "." not in chain and chain not in params:
        return None  # local buffer, not a shared registry
    if INFLIGHT_WORDS & _words(chain):
        return chain
    return None


def _open_chain(node: ast.AST) -> Optional[str]:
    chain = _chain(node)
    if chain is not None and OPEN_WORDS & _words(chain):
        return chain
    return None


def _stmt_suspends(stmt: ast.stmt) -> Optional[ast.AST]:
    """The highest-stakes suspension inside ``stmt`` (nested defs opaque):
    a ``yield`` wins over an ``await`` when both occur, because the yield
    is where ``aclose()``/GeneratorExit can end the function for good —
    the exit path the incident this rule guards actually took."""
    first_await: Optional[ast.AST] = None

    def walk(node: ast.AST) -> Optional[ast.AST]:
        nonlocal first_await
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            if isinstance(child, (ast.Yield, ast.YieldFrom)):
                return child
            if isinstance(child, ast.Await) and first_await is None:
                first_await = child
            found = walk(child)
            if found is not None:
                return found
        return None

    return walk(stmt) or first_await


class _Acquire:
    __slots__ = ("kind", "chain", "stmt", "block_id", "index", "node")

    def __init__(self, kind: str, chain: str, stmt: ast.stmt,
                 block_id: int, index: int, node: ast.AST):
        self.kind = kind
        self.chain = chain
        self.stmt = stmt
        self.block_id = block_id
        self.index = index
        self.node = node


def _collect(fn: ast.AST, params: Set[str]):
    """(acquires, releases, blocks) for one function body.

    ``releases`` maps (kind, chain) -> list of (in_finally, block_id,
    index).  ``blocks`` maps block_id -> the statement list, for the
    straight-line check.  Releases inside NESTED defs are collected as
    finally-equivalent: a closure owning the release (``drop_stream``,
    ``finish_span``) is a delegated-owner contract this per-function
    analysis accepts — the closure is defined precisely to be called on
    every exit arm.
    """
    acquires: List[_Acquire] = []
    releases: Dict[Tuple[str, str], List[Tuple[bool, int, int]]] = {}
    blocks: Dict[int, List[ast.stmt]] = {}

    def shallow_walk(stmt: ast.stmt):
        """The statement and its expression-level descendants — nested
        statement lists (a compound's body/orelse/finalbody/handlers) are
        visited by walk_body's own recursion, so scanning them here would
        double-report one acquire at two nesting levels."""
        stack: List[ast.AST] = [stmt]
        while stack:
            node = stack.pop()
            yield node
            for fname, value in ast.iter_fields(node):
                if fname in ("body", "orelse", "finalbody", "handlers") \
                        and isinstance(node, ast.stmt) \
                        and not isinstance(node, (ast.Assign, ast.AnnAssign)):
                    continue
                if isinstance(value, ast.AST):
                    stack.append(value)
                elif isinstance(value, list):
                    stack.extend(v for v in value if isinstance(v, ast.AST))

    def stmt_releases(stmt: ast.stmt) -> List[Tuple[str, str]]:
        out: List[Tuple[str, str]] = []
        for sub in shallow_walk(stmt):
            if isinstance(sub, ast.Call):
                name = call_name(sub)
                if name == "close" and isinstance(sub.func, ast.Attribute):
                    chain = _open_chain(sub.func.value)
                    if chain is not None:
                        out.append(("open", chain))
                elif name in ("pop", "clear") and isinstance(
                    sub.func, ast.Attribute
                ):
                    chain = _inflight_chain(sub.func.value, params)
                    if chain is not None:
                        out.append(("inflight", chain))
                elif name == SPAN_EMIT:
                    for kw in sub.keywords:
                        if kw.arg == "span_id":
                            for ref in ast.walk(kw.value):
                                c = _chain(ref)
                                if c is not None:
                                    out.append(("span", c))
            elif isinstance(sub, ast.Delete):
                for t in sub.targets:
                    tgt = t.value if isinstance(t, ast.Subscript) else t
                    chain = _inflight_chain(tgt, params)
                    if chain is not None:
                        out.append(("inflight", chain))
        return out

    def stmt_acquires(stmt: ast.stmt) -> List[Tuple[str, str, ast.AST]]:
        out: List[Tuple[str, str, ast.AST]] = []
        for sub in shallow_walk(stmt):
            if isinstance(sub, ast.Call) and call_name(sub) == "open" \
                    and isinstance(sub.func, ast.Attribute):
                chain = _open_chain(sub.func.value)
                if chain is not None:
                    out.append(("open", chain, sub))
            elif isinstance(sub, (ast.Assign, ast.AnnAssign)):
                value = getattr(sub, "value", None)
                targets = (
                    sub.targets if isinstance(sub, ast.Assign)
                    else [sub.target]
                )
                for t in targets:
                    if isinstance(t, ast.Subscript):
                        chain = _inflight_chain(t.value, params)
                        if chain is not None:
                            out.append(("inflight", chain, sub))
                if (
                    isinstance(value, ast.Call)
                    and call_name(value) == SPAN_ALLOC
                ):
                    for t in targets:
                        chain = _chain(t)
                        if chain is not None:
                            out.append(("span", chain, sub))
                elif (
                    # ``sid = span_id or new_span_id()`` and friends: any
                    # RHS that CALLS the allocator binds a span identity.
                    value is not None
                    and any(
                        isinstance(s, ast.Call) and call_name(s) == SPAN_ALLOC
                        for s in ast.walk(value)
                    )
                ):
                    for t in targets:
                        chain = _chain(t)
                        if chain is not None:
                            out.append(("span", chain, sub))
        return out

    def walk_body(body: List[ast.stmt], in_finally: bool) -> None:
        bid = id(body)
        blocks[bid] = body
        for i, stmt in enumerate(body):
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # Delegated-owner closures: their releases satisfy any
                # acquire (finally-equivalent) but their acquires are
                # their own scope's problem.
                for sub_stmt in ast.walk(stmt):
                    if isinstance(sub_stmt, ast.stmt) and sub_stmt is not stmt:
                        for kind, chain in stmt_releases(sub_stmt):
                            releases.setdefault((kind, chain), []).append(
                                (True, bid, i)
                            )
                continue
            if isinstance(stmt, ast.ClassDef):
                continue
            for kind, chain, node in stmt_acquires(stmt):
                acquires.append(_Acquire(kind, chain, stmt, bid, i, node))
            for kind, chain in stmt_releases(stmt):
                releases.setdefault((kind, chain), []).append(
                    (in_finally, bid, i)
                )
            for field, value in ast.iter_fields(stmt):
                if field == "finalbody":
                    walk_body(value, True)
                elif isinstance(value, list) and value and isinstance(
                    value[0], ast.stmt
                ):
                    walk_body(value, in_finally)
                elif field == "handlers":
                    for h in value:
                        walk_body(h.body, in_finally)

    walk_body(list(fn.body), False)
    return acquires, releases, blocks


#: Human names for messages.
KIND_LABEL = {
    "open": "acquired resource",
    "inflight": "registered in-flight entry",
    "span": "opened span identity",
}
KIND_RELEASE = {
    "open": ".close()",
    "inflight": ".pop()/del",
    "span": "add_span(span_id=...)",
}


def check_tc15(sf: SourceFile, ctx: ProjectContext) -> Iterator[Violation]:
    del ctx
    if not _in_scope(sf):
        return iter(())
    out: List[Violation] = []

    for fn, _cls in iter_functions(sf.tree):
        acquires, releases, blocks = _collect(fn, param_names(fn))
        if not acquires:
            continue
        for acq in acquires:
            rels = releases.get((acq.kind, acq.chain), [])
            # Same-statement self-release (``q = R.pop() ... R[k] = q`` in
            # one expression) can't pair an acquire with itself.
            rels = [
                r for r in rels
                if not (r[1] == acq.block_id and r[2] == acq.index
                        and acq.kind != "span")
            ]
            if any(in_fin for in_fin, _, _ in rels):
                continue  # finally runs on every exit path, aclose included
            # Straight-line: a later release in the same block with no
            # suspension/return/raise between.
            body = blocks[acq.block_id]
            satisfied = False
            first_suspend: Optional[ast.AST] = None
            for in_fin, bid, idx in rels:
                if bid != acq.block_id or idx <= acq.index:
                    continue
                clean = True
                for between in body[acq.index + 1: idx]:
                    if isinstance(between, (ast.Return, ast.Raise)):
                        clean = False
                        break
                    s = _stmt_suspends(between)
                    if s is not None:
                        first_suspend = first_suspend or s
                        clean = False
                        break
                if clean:
                    satisfied = True
                    break
            if satisfied:
                continue
            if first_suspend is None:
                # Note the first suspension after the acquire, for the
                # message (the path the release never covers).
                for later in body[acq.index + 1:]:
                    s = _stmt_suspends(later)
                    if s is not None:
                        first_suspend = s
                        break
            detail = f"no matching {KIND_RELEASE[acq.kind]} found"
            if rels:
                detail = (
                    f"the matching {KIND_RELEASE[acq.kind]} is not in a "
                    "`finally` and not straight-line after the acquire"
                )
            if first_suspend is not None:
                what = ("yield" if isinstance(
                    first_suspend, (ast.Yield, ast.YieldFrom)
                ) else "await")
                extra = (
                    " — a generator closed at that yield (aclose()/"
                    "GeneratorExit) never reaches the release"
                    if what == "yield" else
                    " — a cancellation or exception at that await "
                    "skips the release"
                )
                detail += (
                    f"; the {what} at line {first_suspend.lineno} can exit "
                    f"first{extra}"
                )
            out.append(Violation(
                "TC15",
                sf.path,
                acq.stmt.lineno,
                f"{KIND_LABEL[acq.kind]} `{acq.chain}` is not released on "
                f"every exit path: {detail} (the finish-after-final-yield "
                "span-leak class) — move the release into a `finally`, or "
                "waive naming the releasing owner",
                end_line=getattr(acq.stmt, "end_lineno", None),
            ))
    return iter(out)
