"""TC06: every literal metric name must be declared in METRICS_CATALOG.

A typo'd gauge name (``engine_queue_dept``) doesn't fail anything — it
silently splits the time series and every dashboard keyed on the real name
reads zero.  ``utils/metrics.py`` carries the one catalogue of legal names;
this rule checks each literal string handed to the registry's write
(``inc``/``set_gauge``/``observe``) *and* read (``counter``/``gauge``/
``percentile``/``rate``) methods against it — reads too, so the
``/healthz`` payload can only report catalogued gauges.
"""

from __future__ import annotations

import ast
from typing import Iterator, List

from tools.tunnelcheck.core import ProjectContext, SourceFile, Violation

WRITE_METHODS = {"inc", "set_gauge", "observe", "set_labeled_gauge",
                 "prune_labeled_gauge"}
READ_METHODS = {"counter", "gauge", "percentile", "rate", "labeled_gauge"}


def check_tc06(sf: SourceFile, ctx: ProjectContext) -> Iterator[Violation]:
    catalogue = ctx.metrics_names
    if not catalogue:
        return iter(())
    out: List[Violation] = []
    for node in ast.walk(sf.tree):
        if not (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in (WRITE_METHODS | READ_METHODS)
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
        ):
            continue
        name = node.args[0].value
        if name not in catalogue:
            kind = "write" if node.func.attr in WRITE_METHODS else "read"
            out.append(
                Violation(
                    "TC06",
                    sf.path,
                    node.lineno,
                    f"metric {kind} `{node.func.attr}(\"{name}\", ...)` uses "
                    "a name not declared in utils.metrics.METRICS_CATALOG — "
                    "a typo here silently splits the time series; declare it "
                    "or fix the spelling",
                    end_line=node.end_lineno,
                )
            )
    return iter(out)
