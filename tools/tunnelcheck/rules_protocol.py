"""TC05: MessageType dispatch exhaustiveness + typed-error code registry.

A new frame type (FLOW was the last) lands by editing the enum; every
``if msg.msg_type == MessageType.X`` ladder that silently drops unknown
frames then mis-handles the new type with no trace.  The rule requires
each dispatch ladder to either compare against every enum member or carry
an explicit ``else`` acknowledging the remainder.

The second half guards the typed ERROR vocabulary: ``typed_error`` codes
and ``tunnel_code`` class attributes must come from
``protocol.frames.ERROR_CODES`` — a free-string code would fail every
peer's ``error_code()`` dispatch while looking fine locally.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Set, Tuple

from tools.tunnelcheck.core import (
    ProjectContext,
    SourceFile,
    Violation,
    resolve_dotted,
)


def _member_of(node: ast.AST, members: Set[str], sf: SourceFile) -> Optional[str]:
    """"X" when node is ``<...>.MessageType.X`` — through import aliases too
    (``from ...frames import MessageType as MT`` → ``MT.X``)."""
    if not isinstance(node, ast.Attribute) or node.attr not in members:
        return None
    base = resolve_dotted(node.value, sf.aliases)
    if base and base.split(".")[-1] == "MessageType":
        return node.attr
    return None


def _members_in_test(
    test: ast.AST, members: Set[str], sf: SourceFile
) -> Tuple[Set[str], Set[str]]:
    """(member names compared, dump of each subject expression).

    The subject is the non-MessageType side (``msg.msg_type`` in
    ``msg.msg_type == MessageType.X``): a ladder is one dispatch only when
    every link tests the SAME subject.
    """
    found: Set[str] = set()
    subjects: Set[str] = set()
    for node in ast.walk(test):
        if not isinstance(node, ast.Compare):
            continue
        for op, rhs in zip(node.ops, node.comparators):
            if isinstance(op, ast.Eq):
                for side, other in ((node.left, rhs), (rhs, node.left)):
                    m = _member_of(side, members, sf)
                    if m:
                        found.add(m)
                        subjects.add(ast.dump(other))
            elif isinstance(op, ast.In) and isinstance(rhs, (ast.Tuple, ast.List, ast.Set)):
                for e in rhs.elts:
                    m = _member_of(e, members, sf)
                    if m:
                        found.add(m)
                        subjects.add(ast.dump(node.left))
    return found, subjects


def _elif_of(outer: ast.If) -> Optional[ast.If]:
    """The ``elif`` continuing ``outer``, or None.

    An ``elif`` is stored as a lone If in ``orelse`` at the SAME column as
    its parent; an ``else:`` whose body happens to start with an ``if`` is
    indented deeper and must count as an explicit default, not a link.
    """
    if (
        len(outer.orelse) == 1
        and isinstance(outer.orelse[0], ast.If)
        and outer.orelse[0].col_offset == outer.col_offset
    ):
        return outer.orelse[0]
    return None


def _chain(head: ast.If) -> Tuple[List[ast.If], List[ast.stmt]]:
    """All If links of an if/elif ladder plus the final ``else`` body."""
    links = [head]
    cur = head
    while True:
        nxt = _elif_of(cur)
        if nxt is None:
            return links, cur.orelse
        cur = nxt
        links.append(cur)


def check_tc05(sf: SourceFile, ctx: ProjectContext) -> Iterator[Violation]:
    out: List[Violation] = []
    members = set(ctx.message_types)

    if members:
        elif_links: Set[int] = set()
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.If):
                nxt = _elif_of(node)
                if nxt is not None:
                    elif_links.add(id(nxt))
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.If) or id(node) in elif_links:
                continue
            links, final_else = _chain(node)
            handled: Set[str] = set()
            subjects: Set[str] = set()
            dispatch_links = 0
            for link in links:
                in_test, link_subjects = _members_in_test(
                    link.test, members, sf
                )
                if in_test:
                    dispatch_links += 1
                    handled |= in_test
                    subjects |= link_subjects
            if dispatch_links < 2:
                continue  # a lone guard (e.g. `!= HELLO` handshake check)
            if len(subjects) > 1:
                # Links compare DIFFERENT expressions against members —
                # not one dispatch over a single frame's type.
                continue
            if final_else:
                continue
            missing = sorted(members - handled)
            if missing:
                out.append(
                    Violation(
                        "TC05",
                        sf.path,
                        node.lineno,
                        "MessageType dispatch handles "
                        f"{len(handled)}/{len(members)} members with no "
                        f"`else` — unhandled: {', '.join(missing)}; add an "
                        "explicit default branch or handle every member",
                        end_line=node.test.end_lineno,
                    )
                )

    codes = ctx.error_codes
    if codes:
        for node in ast.walk(sf.tree):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "typed_error"
            ):
                code_node = None
                if len(node.args) >= 2:
                    code_node = node.args[1]
                else:
                    for kw in node.keywords:
                        if kw.arg == "code":
                            code_node = kw.value
                if (
                    isinstance(code_node, ast.Constant)
                    and isinstance(code_node.value, str)
                    and code_node.value not in codes
                ):
                    out.append(
                        Violation(
                            "TC05",
                            sf.path,
                            node.lineno,
                            f"typed_error code `{code_node.value}` is not in "
                            "protocol.frames.ERROR_CODES "
                            f"({', '.join(sorted(codes))}); register it "
                            "there or reuse an existing code",
                            end_line=node.end_lineno,
                        )
                    )
            targets: List[ast.expr] = []
            if isinstance(node, ast.Assign):
                targets = node.targets
                value = node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets = [node.target]
                value = node.value
            for target in targets:
                if (
                    isinstance(target, ast.Name)
                    and target.id == "tunnel_code"
                    and isinstance(value, ast.Constant)
                    and isinstance(value.value, str)
                    and value.value not in codes
                ):
                    out.append(
                        Violation(
                            "TC05",
                            sf.path,
                            node.lineno,
                            f"tunnel_code `{value.value}` is not in "
                            "protocol.frames.ERROR_CODES "
                            f"({', '.join(sorted(codes))})",
                            end_line=node.end_lineno,
                        )
                    )
    return iter(out)
