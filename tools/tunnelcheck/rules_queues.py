"""TC10: every queue/buffer on the frame-mux path must declare its bound.

The 1k-client ingress audit (ISSUE 7): an ``asyncio.Queue()`` or ``deque()``
with no ``maxsize``/``maxlen`` in ``endpoints/``, ``transport/``, or
``protocol/`` is a place where a slow reader or a hot sender can buffer
without limit — exactly the class of bug FLOW credit and the coalescer's
byte window exist to prevent.  Every construction must either pass an
explicit bound or carry a per-line waiver *stating who provides the
backpressure* (e.g. "bounded in bytes by FLOW credit"), so the audit is
re-checkable instead of review folklore.

An explicit literal ``maxsize=0`` / ``maxlen=None`` still flags: that
spelling asserts unboundedness without naming the compensating mechanism —
say it in a waiver.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional

from tools.tunnelcheck.core import (
    ProjectContext,
    SourceFile,
    Violation,
    resolve_dotted,
)

#: Directories whose queue constructions are on (or adjacent to) the
#: proxy<->serve frame-mux path.  engine/ is deliberately out of scope:
#: its per-request queues are bounded by max_new_tokens per stream and
#: audited by the serving-path rules (TC07).
SCOPE_DIRS = frozenset({"endpoints", "transport", "protocol"})

#: Constructors that allocate an unbounded buffer unless told otherwise,
#: mapped to the keyword that bounds them and its positional index.
QUEUE_CTORS = {
    "asyncio.Queue": ("maxsize", 0),
    "asyncio.LifoQueue": ("maxsize", 0),
    "asyncio.PriorityQueue": ("maxsize", 0),
    "asyncio.queues.Queue": ("maxsize", 0),
    "collections.deque": ("maxlen", 1),
    "deque": ("maxlen", 1),
}


def _bound_expr(node: ast.Call, kw_name: str, pos_idx: int) -> Optional[ast.AST]:
    """The expression bounding this construction, or None when absent."""
    for kw in node.keywords:
        if kw.arg == kw_name:
            return kw.value
    if len(node.args) > pos_idx:
        return node.args[pos_idx]
    return None


def _explicitly_unbounded(expr: ast.AST) -> bool:
    """Literal 0 / None bounds assert unboundedness rather than a limit."""
    return isinstance(expr, ast.Constant) and expr.value in (0, None)


def check_tc10(sf: SourceFile, ctx: ProjectContext) -> Iterator[Violation]:
    if not (SCOPE_DIRS & set(sf.path.parts)):
        return iter(())
    out: List[Violation] = []
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call):
            continue
        resolved = resolve_dotted(node.func, sf.aliases)
        if resolved not in QUEUE_CTORS:
            continue
        kw_name, pos_idx = QUEUE_CTORS[resolved]
        expr = _bound_expr(node, kw_name, pos_idx)
        if expr is not None and not _explicitly_unbounded(expr):
            continue
        kind = "explicitly unbounded" if expr is not None else "unbounded"
        out.append(
            Violation(
                "TC10",
                sf.path,
                node.lineno,
                f"{kind} `{resolved}(...)` on the frame-mux path — pass an "
                f"explicit {kw_name}= or waive stating who provides the "
                "backpressure (FLOW credit, a byte window, a cwnd, ...)",
                end_line=node.end_lineno,
            )
        )
    return iter(out)
