"""TC11: every retry/backoff loop must be bounded AND jittered (ISSUE 8).

A reconnect or re-dispatch loop that sleeps on a GROWING backoff is the
fabric's herd-behavior control surface.  Without a cap it can sleep for
hours before noticing a healthy peer; without an attempt bound it can court
a dead peer forever; and without a jitter term a fleet of peers killed by
the same fault re-dials the signal server in lockstep — the synchronized
herd the reference's bare ``2·2^(n-1)`` exponential produces at scale.

Detection is by dataflow fingerprint, not naming convention: a ``while`` /
``for`` loop that sleeps (``asyncio.sleep``, ``time.sleep``, or an
``asyncio.wait_for`` timeout) on a duration whose assignments *inside the
loop* grow exponentially (``BASE * 2 ** attempt`` or self-multiplication
like ``backoff *= 2``).  Fixed-interval loops (keepalives, probers) have no
growth and are out of scope.  Each detected retry loop must:

- bound its attempts (``for ... in range(N)``) or cap the backoff (the
  growth expression wrapped in ``min(..., CAP)``), and
- carry a jitter term (a ``random.*`` draw somewhere in the loop body,
  e.g. ``backoff *= 1.0 + random.uniform(0.0, 0.25)``).

An intentional exception carries a per-line waiver on the sleep NAMING the
bound (e.g. ``# tunnelcheck: disable=TC11  RTO deadline capped by RTO_MAX,
jitter-free by design: pacing follows the measured RTT``).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Tuple

from tools.tunnelcheck.core import (
    ProjectContext,
    SourceFile,
    Violation,
    resolve_dotted,
)

#: Directories on the tunnel's reconnect/supervision path; cli.py (the
#: retry supervisor) is scoped by filename.
SCOPE_DIRS = frozenset({"endpoints", "transport"})

SLEEP_FNS = frozenset({"asyncio.sleep", "time.sleep"})
WAIT_FOR_FNS = frozenset({"asyncio.wait_for"})
RANDOM_PREFIXES = ("random.", "numpy.random.", "secrets.")


def _in_scope(sf: SourceFile) -> bool:
    return bool(SCOPE_DIRS & set(sf.path.parts)) or sf.path.name == "cli.py"


def _contains_pow(expr: ast.AST) -> bool:
    return any(
        isinstance(n, ast.BinOp) and isinstance(n.op, ast.Pow)
        for n in ast.walk(expr)
    )


def _contains_self_mult(expr: ast.AST, name: str) -> bool:
    """``expr`` multiplies ``name`` by something (the `backoff *= 2` /
    ``backoff = backoff * 2`` growth spelling)."""
    for n in ast.walk(expr):
        if isinstance(n, ast.BinOp) and isinstance(n.op, ast.Mult):
            for side in (n.left, n.right):
                if isinstance(side, ast.Name) and side.id == name:
                    return True
    return False


def _contains_random(expr: ast.AST, aliases: Dict[str, str]) -> bool:
    for n in ast.walk(expr):
        if isinstance(n, ast.Call):
            resolved = resolve_dotted(n.func, aliases)
            if resolved and resolved.startswith(RANDOM_PREFIXES):
                return True
    return False


def _growth_inside_min(value: ast.AST, name: str) -> bool:
    """Is the exponential/self-mult growth wrapped in a ``min(...)`` cap?"""
    for n in ast.walk(value):
        if (isinstance(n, ast.Call) and isinstance(n.func, ast.Name)
                and n.func.id == "min"):
            for sub in ast.walk(n):
                if _is_growth_node(sub, name):
                    return True
    return False


def _is_growth_node(n: ast.AST, name: str) -> bool:
    if isinstance(n, ast.BinOp) and isinstance(n.op, ast.Pow):
        return True
    if isinstance(n, ast.BinOp) and isinstance(n.op, ast.Mult):
        return any(
            isinstance(s, ast.Name) and s.id == name
            for s in (n.left, n.right)
        )
    return False


@dataclass
class _LoopInfo:
    node: ast.AST
    #: slept-name -> list of (value_expr, is_augassign_mult) assignments
    assigns: Dict[str, List[Tuple[ast.AST, bool]]] = field(
        default_factory=dict
    )
    has_jitter: bool = False
    #: (call node, duration expression) for every sleep in THIS loop
    #: (innermost attribution — a nested loop owns its own sleeps)
    sleeps: List[Tuple[ast.Call, ast.AST]] = field(default_factory=list)

    def bounded_for(self) -> bool:
        return (
            isinstance(self.node, (ast.For, ast.AsyncFor))
            and isinstance(self.node.iter, ast.Call)
            and isinstance(self.node.iter.func, ast.Name)
            and self.node.iter.func.id == "range"
        )


def _duration_expr(call: ast.Call, resolved: str) -> Optional[ast.AST]:
    if resolved in SLEEP_FNS:
        return call.args[0] if call.args else None
    # asyncio.wait_for(aw, timeout): the timeout IS the backoff when a
    # retry loop waits on a stop/backoff race instead of a bare sleep.
    if len(call.args) > 1:
        return call.args[1]
    for kw in call.keywords:
        if kw.arg == "timeout":
            return kw.value
    return None


class _Scanner(ast.NodeVisitor):
    def __init__(self, sf: SourceFile):
        self.sf = sf
        self.stack: List[_LoopInfo] = []
        self.loops: List[_LoopInfo] = []

    # A nested def's body runs when called, not per iteration — its sleeps
    # must not attribute to the enclosing loop (and loops inside it are
    # scanned with a fresh stack).
    def _visit_def(self, node) -> None:
        saved, self.stack = self.stack, []
        self.generic_visit(node)
        self.stack = saved

    visit_FunctionDef = _visit_def
    visit_AsyncFunctionDef = _visit_def
    visit_Lambda = _visit_def

    def _visit_loop(self, node) -> None:
        info = _LoopInfo(node)
        self.loops.append(info)
        self.stack.append(info)
        self.generic_visit(node)
        self.stack.pop()

    visit_While = _visit_loop
    visit_For = _visit_loop
    visit_AsyncFor = _visit_loop

    def visit_Assign(self, node: ast.Assign) -> None:
        if self.stack and len(node.targets) == 1 and isinstance(
                node.targets[0], ast.Name):
            for info in self.stack:
                info.assigns.setdefault(node.targets[0].id, []).append(
                    (node.value, False))
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        if self.stack and isinstance(node.target, ast.Name) and isinstance(
                node.op, ast.Mult):
            for info in self.stack:
                info.assigns.setdefault(node.target.id, []).append(
                    (node.value, True))
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        if self.stack:
            resolved = resolve_dotted(node.func, self.sf.aliases)
            if resolved and resolved.startswith(RANDOM_PREFIXES):
                for info in self.stack:
                    info.has_jitter = True
            if resolved in SLEEP_FNS or resolved in WAIT_FOR_FNS:
                dur = _duration_expr(node, resolved)
                if dur is not None:
                    self.stack[-1].sleeps.append((node, dur))
        self.generic_visit(node)


def _analyze_loop(
    info: _LoopInfo, aliases: Dict[str, str]
) -> Optional[Tuple[ast.Call, bool]]:
    """(anchor sleep call, growth_capped) when this is a retry loop whose
    slept duration grows inside the loop; None otherwise."""
    for call, dur in info.sleeps:
        if _contains_pow(dur):
            return call, _growth_inside_min(dur, "")
        if not isinstance(dur, ast.Name):
            continue
        name = dur.id
        growth: List[Tuple[ast.AST, bool]] = []
        for value, is_aug_mult in info.assigns.get(name, ()):
            if _contains_random(value, aliases):
                continue  # the jitter multiply, not growth
            if _contains_pow(value) or _contains_self_mult(value, name):
                growth.append((value, is_aug_mult))
            elif is_aug_mult and not (
                    isinstance(value, ast.Constant)
                    and isinstance(value.value, (int, float))
                    and value.value <= 1):
                # `backoff *= K`: growth unless K is a literal <= 1.
                growth.append((value, True))
        if growth:
            capped = all(
                not is_aug and _growth_inside_min(value, name)
                for value, is_aug in growth
            )
            return call, capped
    return None


def check_tc11(sf: SourceFile, ctx: ProjectContext) -> Iterator[Violation]:
    if not _in_scope(sf):
        return iter(())
    scanner = _Scanner(sf)
    scanner.visit(sf.tree)
    out: List[Violation] = []
    for info in scanner.loops:
        found = _analyze_loop(info, sf.aliases)
        if found is None:
            continue
        anchor, capped = found
        if not (info.bounded_for() or capped):
            out.append(Violation(
                "TC11", sf.path, anchor.lineno,
                "retry loop's backoff grows without a bound — cap it with "
                "min(..., MAX), bound attempts with `for ... in range(N)`, "
                "or waive naming the bound",
                end_line=anchor.end_lineno,
            ))
        if not info.has_jitter:
            out.append(Violation(
                "TC11", sf.path, anchor.lineno,
                "retry loop sleeps a deterministic backoff — add a jitter "
                "term (e.g. `backoff *= 1 + random.uniform(0, 0.25)`) so a "
                "fleet killed by one fault does not re-dial in lockstep, "
                "or waive explaining why lockstep is safe",
                end_line=anchor.end_lineno,
            ))
    return iter(out)
