"""TC14: client-controlled bytes must pass a registered sanitizer before
reaching a trusted sink.

The PR 7 incident made permanent: before the tenant-identity hardening,
the raw ``x-tunnel-tenant`` header value — client-chosen bytes — flowed
verbatim into the scheduler's fair-admission identity and the per-tenant
metric labels.  A client could mint a fresh identity per request (defeating
its own fair-share cap and diluting everyone else's), bloat the accounting
key space with unbounded label values, and put arbitrary bytes into the
Prometheus exposition.  The fix routed every ingress through
:func:`parse_tenant` (strip, cap at MAX_TENANT_LEN, fingerprint
credentials); this rule makes "every ingress" statically checkable.

Built on the substrate's taint lattice (:mod:`tools.tunnelcheck.dataflow`):
**sources** are client-controlled request data — ``*.headers`` attribute
loads and parameters named ``headers``/``body`` in the package scope;
taint propagates through local assignments, iteration
(``for k, v in headers.items()``), and ordinary calls (a helper fed
tainted bytes returns tainted bytes).  **Sanitizers** launder by
definition: a call to a registered name (``parse_tenant``,
``tenant_fingerprint``, ``prom_label_escape``, the typed parsers, numeric
coercions) yields a clean value whatever it read.  **Sinks** are the
trusted surfaces the incidents hit:

- scheduler identity (``tenant=`` keywords, ``kwargs["tenant"] = ...``,
  the per-tenant accounting calls);
- labeled-metrics values (``set_labeled_gauge``'s label value);
- log interpolation (a tainted value INSIDE the format string — f-string,
  ``%``-formatting, ``.format`` — or a tainted format string itself;
  lazy ``log.info("x %s", v)`` args are exempt: stdlib logging formats
  those without interpreting the value);
- filesystem paths (``open``/``Path``/``os.remove``-class calls);
- relay targets (a ``to=`` keyword or ``{"to": ...}`` payload key — the
  signaling fan-out must never route on unsanitized bytes).

Extending the registries is the intended workflow: a new ingress parser
gets added to SANITIZERS, a new trusted surface to the sink tables, and
the self-run keeps both honest (README "Static analysis & invariants").
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Set, Tuple

from tools.tunnelcheck.core import ProjectContext, SourceFile, Violation
from tools.tunnelcheck.dataflow import (
    TaintPolicy,
    call_name,
    expr_tainted,
    interproc_taint,
    iter_functions,
    param_names,
    taint_locals,
)

SCOPE_PART = "p2p_llm_tunnel_tpu/"

#: Parameter names seeded as tainted in every scoped function: request
#: headers and raw request bodies are client bytes wherever they travel.
TAINTED_PARAMS = frozenset({"headers", "body"})

#: Registered sanitizers: their RESULT is clean regardless of input.
#: strip/cap/validate live behind these names — inline ``.strip()[:64]``
#: chains deliberately do NOT launder (the pre-PR-7 code had partial
#: inline hygiene and still minted identities; centralizing is the point).
SANITIZERS = frozenset({
    "parse_tenant",
    "tenant_fingerprint",
    "prom_label_escape",
    "parse_deadline_ms",
    "parse_trace_context",
    "int",
    "float",
    "bool",
    "len",
})

#: Per-tenant accounting entry points: their first argument is the
#: scheduler/registry identity.
TENANT_SINK_CALLS = frozenset({
    "tenant_begin", "tenant_end", "tenant_shed", "tenant_tokens",
    "charge_tokens",
})

LOG_METHODS = frozenset({
    "debug", "info", "warning", "error", "exception", "critical",
})
LOG_RECEIVER_WORDS = frozenset({"log", "logger", "logging"})

FS_CALLS = frozenset({
    "open", "Path", "remove", "unlink", "makedirs", "rmtree", "mkdir",
})


def _in_scope(sf: SourceFile) -> bool:
    return SCOPE_PART in sf.path.as_posix()


def _is_source(expr: ast.AST) -> bool:
    return (
        isinstance(expr, ast.Attribute)
        and expr.attr == "headers"
        and isinstance(expr.ctx, ast.Load)
    )


def _log_receiver(node: ast.Call) -> bool:
    if not (isinstance(node.func, ast.Attribute)
            and node.func.attr in LOG_METHODS):
        return False
    recv = node.func.value
    name = recv.attr if isinstance(recv, ast.Attribute) else (
        recv.id if isinstance(recv, ast.Name) else ""
    )
    return bool(LOG_RECEIVER_WORDS & set(name.lower().split("_")))


#: One judged sink operand: (expression to judge, sink description, hint).
SinkSpec = Tuple[ast.AST, str, str]


def call_sink_specs(node: ast.Call) -> List[SinkSpec]:
    """Structural sink-operand extraction shared by TC14 (flat lattice)
    and TC21 (interprocedural summaries): every expression that, if
    tainted, lands client bytes on a trusted surface."""
    specs: List[SinkSpec] = []
    name = call_name(node)
    # tenant=/to= keywords anywhere: fair admission / relay routing key
    # on them.
    for kw in node.keywords:
        if kw.arg == "tenant":
            specs.append((kw.value, "the scheduler tenant identity",
                          "parse_tenant"))
        if kw.arg == "to":
            specs.append((kw.value, "a relay `to=` target",
                          "validate the peer id"))
    if name in TENANT_SINK_CALLS and node.args:
        specs.append((node.args[0], f"per-tenant accounting (`{name}`)",
                      "parse_tenant"))
    elif name == "set_labeled_gauge" and len(node.args) >= 3:
        specs.append((node.args[2], "a labeled-metrics value",
                      "prom_label_escape / the bounded registry"))
    elif name in FS_CALLS and node.args:
        specs.append((node.args[0], f"a filesystem path (`{name}`)",
                      "never derive paths from request bytes"))
    elif _log_receiver(node) and node.args:
        fmt = node.args[0]
        hint = "use lazy %s args, which never interpret the value"
        if isinstance(fmt, (ast.JoinedStr, ast.BinOp)):
            specs.append((fmt, "log interpolation", hint))
        elif isinstance(fmt, ast.Call) and call_name(fmt) == "format":
            for a in fmt.args:
                specs.append((a, "log interpolation", hint))
            for kw in fmt.keywords:
                specs.append((kw.value, "log interpolation", hint))
            if isinstance(fmt.func, ast.Attribute):
                specs.append((fmt.func.value, "log interpolation", hint))
        elif not isinstance(fmt, (ast.Constant, ast.Call)):
            specs.append((fmt, "log interpolation", hint))
    # {"to": <tainted>} inside any call payload (signaling sends).
    for a in list(node.args) + [kw.value for kw in node.keywords]:
        if isinstance(a, ast.Dict):
            for k, v in zip(a.keys, a.values):
                if isinstance(k, ast.Constant) and k.value == "to":
                    specs.append((v, "a relay `to=` target",
                                  "validate the peer id"))
    return specs


def assign_sink_specs(node: ast.Assign) -> List[SinkSpec]:
    """``kwargs["tenant"] = <tainted>`` — the scheduler-identity store."""
    specs: List[SinkSpec] = []
    for t in node.targets:
        if (
            isinstance(t, ast.Subscript)
            and isinstance(t.slice, ast.Constant)
            and t.slice.value == "tenant"
        ):
            specs.append((node.value, "the scheduler tenant identity",
                          "parse_tenant"))
    return specs


def check_tc14(sf: SourceFile, ctx: ProjectContext) -> Iterator[Violation]:
    del ctx
    if not _in_scope(sf):
        return iter(())
    out: List[Violation] = []
    reported: Set = set()

    def report(node: ast.AST, sink: str, hint: str) -> None:
        key = (node.lineno, sink)
        if key in reported:
            return
        reported.add(key)
        out.append(Violation(
            "TC14",
            sf.path,
            node.lineno,
            f"client-controlled bytes reach {sink} without a registered "
            f"sanitizer ({hint}) — the x-tunnel-tenant minting hole class: "
            "route through parse_tenant/tenant_fingerprint/"
            "prom_label_escape (or register the new parser in "
            "rules_taint.SANITIZERS), or waive naming why these bytes "
            "are trusted",
            end_line=getattr(node, "end_lineno", None),
        ))

    for fn, _cls in iter_functions(sf.tree):
        seed = param_names(fn) & TAINTED_PARAMS
        tainted = taint_locals(fn, _is_source, SANITIZERS, seed=seed)

        def dirty(expr: Optional[ast.AST]) -> bool:
            return expr is not None and expr_tainted(
                expr, tainted, _is_source, SANITIZERS
            )

        for node in ast.walk(fn):
            if isinstance(node, ast.Assign):
                for expr, sink, hint in assign_sink_specs(node):
                    if dirty(expr):
                        report(node, sink, hint)
                continue
            if not isinstance(node, ast.Call):
                continue
            for expr, sink, hint in call_sink_specs(node):
                if dirty(expr):
                    report(node, sink, hint)
    return iter(out)


# ---------------------------------------------------------------------------
# TC21: interprocedural header taint (ISSUE 18)
# ---------------------------------------------------------------------------
#
# TC14's lattice is per-function: a helper that EXTRACTS a header value
# (``return req.headers.get("x-tunnel-tenant", "")``) returns what TC14
# sees as a clean call result, and a helper that STAMPS its argument into
# a sink (``kw["tenant"] = raw``) hides the sink from its callers — the
# pre-PR-7 minting hole, one function-call deep.  TC21 runs the identical
# source/sanitizer/sink contract through the interprocedural summary
# engine and reports only flows TC14 cannot see (same-line findings are
# TC14's; duplicating them would double every waiver).


def _tc21_sink_args(call: ast.Call) -> List[Tuple[ast.AST, str]]:
    return [(expr, sink) for expr, sink, _hint in call_sink_specs(call)]


def _tc21_sink_assign(node: ast.Assign) -> List[Tuple[ast.AST, str]]:
    return [(expr, sink) for expr, sink, _hint in assign_sink_specs(node)]


def _tc21_engine(ctx: ProjectContext):
    def build():
        policy = TaintPolicy(
            is_source=_is_source,
            sanitizers=SANITIZERS,
            seed_params=TAINTED_PARAMS,
            sink_args=_tc21_sink_args,
            sink_assign=_tc21_sink_assign,
        )
        return interproc_taint(ctx.scoped_callgraph(SCOPE_PART), policy)

    return ctx.interproc("TC21", build)


def warm_tc21(ctx: ProjectContext) -> None:
    _tc21_engine(ctx)


def check_tc21(sf: SourceFile, ctx: ProjectContext) -> Iterator[Violation]:
    if not _in_scope(sf):
        return iter(())
    engine = _tc21_engine(ctx)
    intra_lines = {v.line for v in check_tc14(sf, ctx)}
    out: List[Violation] = []
    reported: Set = set()

    def on_sink(node: ast.AST, sink: str) -> None:
        key = (node.lineno, sink)
        if node.lineno in intra_lines or key in reported:
            return
        reported.add(key)
        out.append(Violation(
            "TC21",
            sf.path,
            node.lineno,
            f"client-controlled bytes reach {sink} through a helper-"
            "function chain without a registered sanitizer — the "
            "x-tunnel-tenant minting hole, one call deep (the flow TC14's "
            "per-function lattice cannot see): sanitize at the ingress "
            "(parse_tenant/tenant_fingerprint/prom_label_escape), or "
            "waive naming why these bytes are trusted",
            end_line=getattr(node, "end_lineno", None),
        ))

    for fn, _cls in iter_functions(sf.tree):
        engine.analyze(fn, on_sink=on_sink)
    return iter(out)


check_tc21.warm = warm_tc21
