"""TC18: KV pages crossing the tier/tunnel boundary must pass the
registered pin check before being spliced into a device pool.

The ISSUE 16 incident class this rule makes permanent: a KV page body
that left the device pool — into the host-RAM spill tier, a snapshot, or
(eventually) a peer's pool over the tunnel — re-enters as *bytes*.  The
pool's layout contract (kv quant mode, quant group size, dtype, head
geometry) travels as metadata NEXT TO those bytes, and nothing about a
``dynamic_update_index_in_dim`` splice checks it: int4-packed bytes write
into an int8 pool without complaint and decode garbage three requests
later, long after the splice that caused it.  PR 2/3 fixed the same hole
for pool *snapshots* by pinning quant mode + group size in the snapshot
sidecar; the spill tier re-opens the boundary on the hot path, so the
check moves into code — :func:`p2p_llm_tunnel_tpu.engine.prefix_cache.
verify_page_pin` — and this rule makes "every splice is pin-checked"
statically enforceable.

Unlike TC14's flow-INsensitive lattice (where a name tainted anywhere is
tainted everywhere), this rule is **flow-sensitive** on the same
substrate primitives (:func:`expr_tainted`, the sanitizer-call laundering
semantics): a forward walk over each function body where

- loading a ``.payload`` attribute (the spill tier's ``_SpillPage`` body,
  a tunnel frame body) or binding a parameter named ``payload`` marks the
  name tainted **from that point on**;
- re-assigning the name from a registered pin check —
  ``payload = verify_page_pin(payload, meta, want)`` — *kills* the taint
  on the fall-through path (the sanctioned idiom: the checked value
  REPLACES the unchecked one, so a later splice can only see the
  laundered binding);
- an except-handler / early-``continue`` path that skips the check never
  merges its tainted state past a ``raise``/``return``/``continue``
  (which is exactly how the engine's page-in loop drops a failing page
  to the re-prefill fallback without ever reaching the splice).

**Sinks** are the device-pool splice surfaces: calls named
``page_in`` / ``_page_in_op`` (the jitted scatter op and its engine
handle), ``jax.lax.dynamic_update_index_in_dim``, and ``.at[...].set``
buffer writes.  Feeding any of them a tainted page body flags; route the
body through ``verify_page_pin`` first (or register a new boundary check
here), or waive naming why the bytes cannot have crossed a tier boundary.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Set

from tools.tunnelcheck.core import ProjectContext, SourceFile, Violation
from tools.tunnelcheck.dataflow import (
    TaintPolicy,
    call_name,
    expr_tainted,
    interproc_taint,
    iter_functions,
    param_names,
)

SCOPE_PART = "p2p_llm_tunnel_tpu/"

#: Parameter name seeded as tainted: a raw page body handed across a
#: function boundary.  (``page`` is deliberately NOT seeded — the jitted
#: splice primitive itself takes ``page`` and must stay definable.)
TAINTED_PARAMS = frozenset({"payload"})

#: Registered tier-boundary checks: their RESULT is a verified page body.
SANITIZERS = frozenset({"verify_page_pin"})

#: Device-pool splice entry points: a tainted argument here is unchecked
#: bytes landing in pool memory.
SPLICE_CALLS = frozenset({"page_in", "_page_in_op",
                          "dynamic_update_index_in_dim"})


def _in_scope(sf: SourceFile) -> bool:
    return SCOPE_PART in sf.path.as_posix()


def _is_source(expr: ast.AST) -> bool:
    return (
        isinstance(expr, ast.Attribute)
        and expr.attr == "payload"
        and isinstance(expr.ctx, ast.Load)
    )


def _walk_same_scope(node: ast.AST) -> Iterator[ast.AST]:
    """``ast.walk`` minus nested def/lambda bodies (they rebind params and
    get their own :func:`iter_functions` pass)."""
    stack = [node]
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        yield n
        stack.extend(ast.iter_child_nodes(n))


def _at_set_buffer_write(node: ast.Call) -> bool:
    """``arr.at[...].set(x)`` / ``.add(x)`` — the functional buffer write."""
    return (
        isinstance(node.func, ast.Attribute)
        and node.func.attr in ("set", "add")
        and isinstance(node.func.value, ast.Subscript)
        and isinstance(node.func.value.value, ast.Attribute)
        and node.func.value.value.attr == "at"
    )


class _Flow:
    """Flow-sensitive forward taint walk over one function body.

    State is the set of tainted local names at the current program point;
    ``None`` stands for "all paths left this body" (return/raise/break/
    continue), which is how a skip-the-check error path is excluded from
    the join after a ``try``.  Joins are set unions; loops run to a small
    fixpoint (the lattice is finite and monotone, 4 passes bound it far
    past any real nesting)."""

    def __init__(self, on_sink) -> None:
        self.on_sink = on_sink
        self._breaks: List[Set[str]] = []
        self._continues: List[Set[str]] = []

    # -- sinks ----------------------------------------------------------

    def _dirty(self, expr: Optional[ast.AST], state: Set[str]) -> bool:
        return expr is not None and expr_tainted(
            expr, state, _is_source, SANITIZERS
        )

    def scan(self, expr: Optional[ast.AST], state: Set[str]) -> None:
        if expr is None:
            return
        for sub in _walk_same_scope(expr):
            if not isinstance(sub, ast.Call):
                continue
            args = list(sub.args) + [kw.value for kw in sub.keywords]
            name = call_name(sub)
            if name in SPLICE_CALLS and any(
                self._dirty(a, state) for a in args
            ):
                self.on_sink(sub, f"`{name}`")
            elif _at_set_buffer_write(sub) and any(
                self._dirty(a, state) for a in args
            ):
                self.on_sink(sub, "an `.at[...].set` buffer write")

    # -- transfer -------------------------------------------------------

    def run_body(self, body, state: Optional[Set[str]]) -> Optional[Set[str]]:
        cur = state
        for stmt in body:
            if cur is None:
                break
            cur = self.stmt(stmt, cur)
        return cur

    @staticmethod
    def _join(a: Optional[Set[str]], b: Optional[Set[str]]) -> Optional[Set[str]]:
        if a is None:
            return b
        if b is None:
            return a
        return a | b

    @staticmethod
    def _target_names(target: ast.AST) -> Iterator[str]:
        if isinstance(target, ast.Name):
            yield target.id
        elif isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                yield from _Flow._target_names(e)

    def stmt(self, node: ast.stmt, cur: Set[str]) -> Optional[Set[str]]:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return cur
        if isinstance(node, (ast.Return, ast.Raise)):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.expr):
                    self.scan(child, cur)
            return None
        if isinstance(node, ast.Break):
            if self._breaks:
                self._breaks[-1] |= cur
            return None
        if isinstance(node, ast.Continue):
            if self._continues:
                self._continues[-1] |= cur
            return None
        if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            value = getattr(node, "value", None)
            if value is None:
                return cur
            self.scan(value, cur)
            tainted = self._dirty(value, cur)
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            out = set(cur)
            for t in targets:
                names = set(self._target_names(t))
                if tainted:
                    out |= names
                elif not isinstance(node, ast.AugAssign):
                    # The kill: a clean (e.g. sanitizer-call) re-assign
                    # launders the name on this path — the flow-sensitive
                    # step TC14's everywhere-tainted lattice cannot take.
                    out -= names
                if (tainted and isinstance(t, ast.Subscript)
                        and isinstance(t.value, ast.Name)):
                    # Storing tainted bytes INTO a container taints it.
                    out.add(t.value.id)
            return out
        if isinstance(node, ast.If):
            self.scan(node.test, cur)
            a = self.run_body(node.body, set(cur))
            b = self.run_body(node.orelse, set(cur))
            return self._join(a, b)
        if isinstance(node, (ast.While, ast.For, ast.AsyncFor)):
            head = set(cur)
            self._breaks.append(set())
            self._continues.append(set())
            for _ in range(4):
                entry = set(head)
                if isinstance(node, ast.While):
                    self.scan(node.test, entry)
                else:
                    self.scan(node.iter, entry)
                    if self._dirty(node.iter, entry):
                        entry |= set(self._target_names(node.target))
                out = self.run_body(node.body, entry)
                new_head = set(head) | self._continues[-1]
                if out is not None:
                    new_head |= out
                if new_head == head:
                    break
                head = new_head
            self._continues.pop()
            after = head | self._breaks.pop()
            if node.orelse:
                o = self.run_body(node.orelse, set(after))
                after = o if o is not None else after
            return after
        if isinstance(node, (ast.With, ast.AsyncWith)):
            st = set(cur)
            for item in node.items:
                self.scan(item.context_expr, st)
                if item.optional_vars is not None and self._dirty(
                    item.context_expr, st
                ):
                    st |= set(self._target_names(item.optional_vars))
            return self.run_body(node.body, st)
        if isinstance(node, ast.Try):
            body_out = self.run_body(node.body, set(cur))
            # Any statement in the body may raise: handlers see the state
            # at entry joined with the body's fall-through state.
            h_in = set(cur) | (body_out or set())
            outs: List[Set[str]] = []
            if body_out is not None:
                else_out = (self.run_body(node.orelse, set(body_out))
                            if node.orelse else body_out)
                if else_out is not None:
                    outs.append(else_out)
            for handler in node.handlers:
                ho = self.run_body(handler.body, set(h_in))
                if ho is not None:
                    outs.append(ho)
            joined: Optional[Set[str]] = None
            for o in outs:
                joined = self._join(joined, o)
            if node.finalbody:
                fin_out = self.run_body(
                    node.finalbody, set(h_in) | (joined or set())
                )
                if joined is not None and fin_out is None:
                    joined = None
            return joined
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self.scan(child, cur)
        return cur


def check_tc18(sf: SourceFile, ctx: ProjectContext) -> Iterator[Violation]:
    del ctx
    if not _in_scope(sf):
        return iter(())
    out: List[Violation] = []
    reported: Set = set()

    def report(node: ast.AST, sink: str) -> None:
        key = (node.lineno, sink)
        if key in reported:
            return
        reported.add(key)
        out.append(Violation(
            "TC18",
            sf.path,
            node.lineno,
            f"KV page bytes reach a device-pool splice ({sink}) without "
            "passing the registered tier-boundary pin check — the "
            "quant/group-size pinning contract (PR 2/3 snapshots, ISSUE "
            "16 spill tier): re-assign through verify_page_pin "
            "(`payload = verify_page_pin(payload, meta, want)`) before "
            "the splice (or register the new boundary check in "
            "rules_tierpin.SANITIZERS), or waive naming why these bytes "
            "never crossed a tier boundary",
            end_line=getattr(node, "end_lineno", None),
        ))

    for fn, _cls in iter_functions(sf.tree):
        seed = param_names(fn) & TAINTED_PARAMS
        _Flow(report).run_body(fn.body, set(seed))
    return iter(out)


# ---------------------------------------------------------------------------
# TC20: interprocedural page-boundary pinning (ISSUE 18)
# ---------------------------------------------------------------------------
#
# TC18 sees one function at a time, so a page EXTRACTED in one helper and
# serialized in another is invisible to it — exactly the shape the
# disaggregated-prefill and peer-KV-tier work will introduce.  TC20 runs
# the same source/sanitizer contract through the interprocedural summary
# engine: a value tainted by prefix-pool page extraction (a ``page_out``/
# ``_page_out_op`` pool read, an ``export_state`` tier chain, a
# ``*page*.payload`` body) must pass ``verify_page_pin`` on every path
# before reaching a tunnel send, a tier write (``note_spilled``), or a
# device-pool splice — including when the extraction and the boundary live
# in different functions.

#: Calls whose RESULT is raw page bytes leaving the pool: the jitted
#: gather op and its engine handle, and the exported tier/LRU chain.
PAGE_EXTRACT_CALLS = frozenset({"page_out", "_page_out_op", "export_state"})

#: Tier-write entry points: page bytes entering the host-RAM spill tier.
TIER_WRITE_CALLS = frozenset({"note_spilled"})

#: Tunnel/socket sends: page bytes leaving the process.  Generic names on
#: purpose — every transport layer (fabric, chaos wrapper, signaling,
#: frame clients) exposes ``send``-shaped methods, and the rule only fires
#: when PAGE-tainted bytes reach one, not on ordinary frame traffic.
#: ``kv_pages_chunk`` is the KV_PAGES transfer framer (ISSUE 20): pool
#: bytes entering a transfer frame ARE leaving the process, even when the
#: ``channel.send`` of the encoded frame lives in a different function —
#: registering the framer itself keeps the sink at the semantic boundary.
SEND_CALLS = frozenset({
    "send", "send_bytes", "send_frame", "kv_pages_chunk",
})

#: Words in a receiver name that mark ``x.payload`` as a PAGE body rather
#: than a protocol-frame body (``msg.payload`` is every tunnel message;
#: ``page.payload`` / ``spill.payload`` is pool bytes).  TC18 can afford
#: the broad ``.payload`` source because its sinks only exist in engine
#: code; TC20's send sink would otherwise flag every frame relay.
PAGE_RECEIVER_WORDS = ("page", "spill")


def _is_page_source(expr: ast.AST) -> bool:
    if isinstance(expr, ast.Call):
        return call_name(expr) in PAGE_EXTRACT_CALLS
    if (
        isinstance(expr, ast.Attribute)
        and expr.attr == "payload"
        and isinstance(expr.ctx, ast.Load)
        and isinstance(expr.value, ast.Name)
    ):
        recv = expr.value.id.lower()
        return any(w in recv for w in PAGE_RECEIVER_WORDS)
    return False


def _tc20_sink_args(call: ast.Call):
    name = call_name(call)
    if name in SPLICE_CALLS:
        desc = f"a device-pool splice (`{name}`)"
    elif name in TIER_WRITE_CALLS:
        desc = f"a tier write (`{name}`)"
    elif name in SEND_CALLS:
        desc = f"a tunnel send (`{name}`)"
    elif _at_set_buffer_write(call):
        desc = "an `.at[...].set` buffer write"
    else:
        return []
    args = list(call.args) + [kw.value for kw in call.keywords]
    return [(a, desc) for a in args]


def _tc20_engine(ctx: ProjectContext):
    def build():
        policy = TaintPolicy(
            is_source=_is_page_source,
            sanitizers=SANITIZERS,
            sink_args=_tc20_sink_args,
        )
        return interproc_taint(ctx.scoped_callgraph(SCOPE_PART), policy)

    return ctx.interproc("TC20", build)


def warm_tc20(ctx: ProjectContext) -> None:
    _tc20_engine(ctx)


def check_tc20(sf: SourceFile, ctx: ProjectContext) -> Iterator[Violation]:
    if not _in_scope(sf):
        return iter(())
    engine = _tc20_engine(ctx)
    out: List[Violation] = []
    reported: Set = set()

    def on_sink(node: ast.AST, desc: str) -> None:
        key = (node.lineno, desc)
        if key in reported:
            return
        reported.add(key)
        out.append(Violation(
            "TC20",
            sf.path,
            node.lineno,
            f"extracted KV page bytes reach {desc} without passing "
            "verify_page_pin on every path — the page wire contract "
            "(quant mode + group size pinned, checksum verified) follows "
            "the bytes across function and tier boundaries: re-assign "
            "through verify_page_pin before the boundary (or register "
            "the new check in rules_tierpin.SANITIZERS), or waive naming "
            "the contract that makes these bytes pin-safe",
            end_line=getattr(node, "end_lineno", None),
        ))

    for fn, _cls in iter_functions(sf.tree):
        engine.analyze(fn, on_sink=on_sink)
    return iter(out)


check_tc20.warm = warm_tc20
