"""TC09: span names registered in SPAN_CATALOG; no emission in traced code.

The TC06 pattern applied to the span journal (ISSUE 6): a typo'd span name
(``engine.queue_wiat``) doesn't fail anything — it silently splits a
request's timeline and every traceview rollup keyed on the real name reads
"missing".  ``utils/tracing.py`` carries the one catalogue of legal span
names; every literal string handed to the recorder's emit methods
(``add_span`` / ``add_event``) must appear in it.

Second invariant: span emission is HOST-ONLY.  A recorder call inside a
function this module jits or hands to ``lax.scan`` is a tracer error at
best (the timestamp would be a traced value) and a per-step host sync at
worst — the exact dispatch-path contamination the tracing module exists to
avoid (its charter: zero device dispatches on the serving path, TC07
clean).  Reuses TC03's traced-function discovery so the two rules cannot
disagree about what "traced" means.
"""

from __future__ import annotations

import ast
from typing import Iterator, List

from tools.tunnelcheck.core import ProjectContext, SourceFile, Violation
from tools.tunnelcheck.rules_jax import _traced_functions

#: The recorder's emit surface (utils.tracing.TraceRecorder).
SPAN_EMIT_METHODS = {"add_span", "add_event"}


def check_tc09(sf: SourceFile, ctx: ProjectContext) -> Iterator[Violation]:
    catalogue = ctx.span_names
    traced_ids = {}
    for fn, _statics in _traced_functions(sf, ctx):
        name = getattr(fn, "name", "<lambda>")
        for sub in ast.walk(fn):
            traced_ids.setdefault(id(sub), name)
    out: List[Violation] = []
    for node in ast.walk(sf.tree):
        if not (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in SPAN_EMIT_METHODS
        ):
            continue
        fn_name = traced_ids.get(id(node))
        if fn_name is not None:
            out.append(
                Violation(
                    "TC09",
                    sf.path,
                    node.lineno,
                    f"span emission `{node.func.attr}(...)` inside traced "
                    f"`{fn_name}` — tracing is host-only; a recorder call "
                    "in jitted/scanned code is a tracer error or a "
                    "per-step host sync (move it to the dispatch site)",
                    end_line=node.end_lineno,
                )
            )
        if not (
            catalogue
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
        ):
            continue
        name = node.args[0].value
        if name not in catalogue:
            out.append(
                Violation(
                    "TC09",
                    sf.path,
                    node.lineno,
                    f"span `{node.func.attr}(\"{name}\", ...)` uses a name "
                    "not declared in utils.tracing.SPAN_CATALOG — a typo "
                    "here silently splits the request timeline; declare it "
                    "or fix the spelling",
                    end_line=node.end_lineno,
                )
            )
    return iter(out)
