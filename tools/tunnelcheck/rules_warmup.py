"""TC17: every dispatch-site compiled-program kind must be warmup-reachable.

The engine's readiness contract (ISSUE 12/15): after ``warmup()`` declares
the grid complete, a first-seen program key on the serving path is a
MID-SERVE COLD COMPILE — tens of seconds of stall inside a live request on
the tunneled-TPU deployment.  The runtime detector
(``engine_cold_compiles_total``) catches the hole when traffic hits it;
this rule is its static counterpart: every ``_program_key`` spelling an
engine dispatch site can emit (the literal ``kind`` handed to
``_note_program``/``_program_key``) must be REACHABLE from the warmup/AOT
plan generators — functions named ``warmup*`` or ``_warm*`` (the
``warmup_plan`` enumeration, the per-kind warm methods) — or carry a
per-line waiver naming why that program is allowed to compile on first
use.

The regression class is the ISSUE 5 width-hint hole ``test_warmup_aot``
caught at runtime: chunk-prefill dispatches reached view buckets the
warmup enumeration never visited.  A kind that exists ONLY at a dispatch
site is the same bug one layer earlier — the plan generator cannot even
enumerate shapes for a kind it has never heard of.

Mechanics: per file, literal kinds are collected from two sides —

- **dispatch kinds**: string literals in the first argument of
  ``_note_program(...)``/``_program_key(...)`` calls inside functions NOT
  named like warm generators (an ``IfExp`` first argument contributes
  BOTH branch literals — the ``"prefill_echo" if echo else "prefill"``
  shape must not hide its echo branch);
- **warm kinds**: the same call-argument literals inside warm-named
  functions, plus the FIRST element of any tuple literal there (the
  ``warmup_plan`` ``[(kind, shape), ...]`` enumeration and the AOT jobs
  list both carry kinds in that position).

A dispatch kind absent from the file's warm kinds flags at the dispatch
site.  Files that never call ``_note_program`` are out of scope.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator, List, Optional, Set

from tools.tunnelcheck.core import ProjectContext, SourceFile, Violation

#: The program-accounting entry points whose first argument is a kind.
_KIND_FNS = ("_note_program", "_program_key")

#: Functions whose bodies ARE the warmup/AOT plan: the serial pass, the
#: plan enumeration, and the per-kind warm helpers.
_WARM_NAME_RE = re.compile(r"^(warmup|_warm)")

_MSG = (
    "program kind {kind!r} is dispatched here but unreachable from the "
    "warmup/AOT plan generators (no warmup*/_warm* function in this file "
    "mentions it) — a first-seen key after warmup() is a mid-serve cold "
    "compile (engine_cold_compiles_total, the test_warmup_aot width-hint "
    "hole class); add the kind to warmup_plan()/a _warm_* helper, or "
    "waive naming why first-use compilation is acceptable for it"
)


def _call_name(node: ast.Call) -> Optional[str]:
    fn = node.func
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return None


def _arg0_kinds(node: ast.Call) -> List[str]:
    """Literal kind strings in a kind-fn call's first argument — plain
    constants and BOTH branches of a conditional expression."""
    if not node.args:
        return []
    a = node.args[0]
    if isinstance(a, ast.Constant) and isinstance(a.value, str):
        return [a.value]
    if isinstance(a, ast.IfExp):
        return [
            b.value for b in (a.body, a.orelse)
            if isinstance(b, ast.Constant) and isinstance(b.value, str)
        ]
    return []


def check_tc17(sf: SourceFile, ctx: ProjectContext) -> Iterator[Violation]:
    warm_kinds: Set[str] = set()
    dispatch_sites: List = []  # (node, kinds)
    saw_note = [False]

    def visit_fn(fn, enclosing_warm: Optional[bool]) -> None:
        # A method/module-level def is warm by NAME; a nested def
        # inherits its enclosing function's warmth — a warm-named closure
        # inside a dispatcher is part of the dispatcher (it must not
        # launder the dispatcher's kinds), and a dispatch helper nested
        # inside a warm function runs during warmup.
        if enclosing_warm is None:
            is_warm = bool(_WARM_NAME_RE.match(fn.name))
        else:
            is_warm = enclosing_warm
        stack = list(ast.iter_child_nodes(fn))
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                visit_fn(node, is_warm)
                continue
            if isinstance(node, ast.Call):
                name = _call_name(node)
                if name in _KIND_FNS:
                    kinds = _arg0_kinds(node)
                    if not is_warm:
                        # BOTH spellings are dispatch sites: a program
                        # key minted via _program_key directly (ad-hoc
                        # accounting) is just as reachable-from-serving
                        # as a _note_program call.
                        saw_note[0] = True
                        if kinds:
                            dispatch_sites.append((node, kinds))
                    else:
                        warm_kinds.update(kinds)
            elif is_warm and isinstance(node, ast.Tuple) and node.elts:
                # The plan enumeration's ("kind", shape) tuples and the
                # AOT jobs list's leading-label tuples.
                first = node.elts[0]
                if (isinstance(first, ast.Constant)
                        and isinstance(first.value, str)):
                    warm_kinds.add(first.value)
            stack.extend(ast.iter_child_nodes(node))

    def visit_scope(body) -> None:
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                visit_fn(node, None)
            elif isinstance(node, ast.ClassDef):
                visit_scope(node.body)

    visit_scope(sf.tree.body)
    if not saw_note[0]:
        return iter(())
    out: List[Violation] = []
    for node, kinds in dispatch_sites:
        for kind in sorted(set(kinds) - warm_kinds):
            out.append(Violation(
                "TC17", sf.path, node.lineno,
                _MSG.format(kind=kind),
                end_line=node.end_lineno,
            ))
    return iter(out)
