"""SARIF 2.1.0 export: machine-consumable findings (`--sarif out.json`).

One run object, one tool driver (``tunnelcheck``), one result per
violation.  Waived findings are included as suppressed results
(``suppressions: [{kind: "inSource"}]`` — the waiver comment IS the
in-source suppression), so a SARIF consumer can audit what the waivers
hide exactly like ``--show-waived`` does on the CLI.

The shape follows the published 2.1.0 schema
(https://json.schemastore.org/sarif-2.1.0.json): ``version`` and
``$schema`` at the top, ``runs[].tool.driver.rules`` carrying one
reportingDescriptor per rule id (``results[].ruleIndex`` points into it),
and physical locations with repo-relative URIs under a ``SRCROOT``
uriBaseId.  ``tests/test_tunnelcheck.py`` pins this shape — a field
rename here fails fast instead of silently breaking downstream ingestion.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Dict, List, Optional, Sequence

from tools.tunnelcheck.core import RULE_SUMMARIES, Violation

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = "https://json.schemastore.org/sarif-2.1.0.json"


def _uri(path: Path, root: Optional[Path]) -> str:
    p = path
    if root is not None:
        try:
            p = path.resolve().relative_to(root.resolve())
        except ValueError:
            pass
    return p.as_posix()


def to_sarif(
    active: Sequence[Violation],
    waived: Sequence[Violation] = (),
    root: Optional[Path] = None,
) -> Dict:
    """The SARIF log dict for one run (serialize with :func:`write_sarif`)."""
    rule_ids = sorted(RULE_SUMMARIES)
    rule_index = {rid: i for i, rid in enumerate(rule_ids)}

    def result(v: Violation, suppressed: bool) -> Dict:
        region: Dict = {"startLine": max(1, v.line)}
        if v.end_line is not None and v.end_line >= v.line:
            region["endLine"] = v.end_line
        out: Dict = {
            "ruleId": v.rule,
            "ruleIndex": rule_index.get(v.rule, -1),
            "level": "error",
            "message": {"text": v.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {
                        "uri": _uri(v.path, root),
                        "uriBaseId": "SRCROOT",
                    },
                    "region": region,
                },
            }],
        }
        if suppressed:
            out["suppressions"] = [{
                "kind": "inSource",
                "justification": "tunnelcheck: disable waiver comment",
            }]
        return out

    results: List[Dict] = [result(v, False) for v in active]
    results += [result(v, True) for v in waived]

    log: Dict = {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [{
            "tool": {
                "driver": {
                    "name": "tunnelcheck",
                    "informationUri":
                        "README.md#static-analysis--invariants",
                    "rules": [
                        {
                            "id": rid,
                            "shortDescription": {
                                "text": RULE_SUMMARIES[rid]
                            },
                        }
                        for rid in rule_ids
                    ],
                },
            },
            "columnKind": "unicodeCodePoints",
            "results": results,
        }],
    }
    if root is not None:
        log["runs"][0]["originalUriBaseIds"] = {
            "SRCROOT": {"uri": root.resolve().as_uri() + "/"}
        }
    return log


def write_sarif(
    path: Path,
    active: Sequence[Violation],
    waived: Sequence[Violation] = (),
    root: Optional[Path] = None,
) -> None:
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(
        json.dumps(to_sarif(active, waived, root=root), indent=2) + "\n",
        encoding="utf-8",
    )
